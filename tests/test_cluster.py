"""Replicated serving-cluster tests: routing policies, typed admission
control, priority aging (no starvation), deadlines/cancellation, drain,
and the headline guarantee — greedy output through the cluster is BITWISE
identical to a single no-fault engine even when a replica crashes
mid-request (exact, bucketed, chunked and speculative paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.cluster import (
    DEAD,
    DEGRADED,
    HEALTHY,
    FaultPlan,
    Frontend,
    FrontendConfig,
    PrefixAffinityRouter,
    ReplicaHandle,
    RoundRobinRouter,
    least_loaded,
    make_router,
    prefix_route_key,
)
from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate
from tpu_parallel.obs.registry import MetricRegistry
from tpu_parallel.serving import (
    CANCELLED,
    EXPIRED,
    FAILED,
    FINISHED,
    REJECT_CLIENT_LIMIT,
    REJECT_DRAINING,
    REJECT_QUEUE_FULL,
    REJECT_TOKEN_BUDGET,
    REJECTED,
    FIFOScheduler,
    Request,
    RequestOutput,
    SchedulerConfig,
    ServingEngine,
)


@pytest.fixture(scope="module")
def env():
    """One tiny model + a mixed-length prompt set + greedy references,
    shared by every device-driving test in this file."""
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    rng = jax.random.PRNGKey(7)
    lens = [3, 9, 6, 12, 5, 7]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    probe = jax.random.randint(rng, (1, max(lens)), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=8,
        ))[0]
        for p in prompts
    ]
    return cfg, model, params, prompts, refs


def _engine(env, clock=None, **kw):
    cfg, model, params, _, _ = env
    # per-step decode tick by default: the fault-injection choreography
    # in this file (crash_at_tick / stall windows / retry counts) is
    # pinned at one-token-per-tick granularity so crashes land
    # mid-request; the FUSED default is covered by
    # test_crash_midflight_exact_fused_tick and the serving parity suite
    kwargs = dict(
        n_slots=2, scheduler=SchedulerConfig(max_prefills_per_tick=2),
        decode_steps_per_tick=1,
    )
    kwargs.update(kw)
    if clock is not None:
        kwargs["clock"] = clock
    return ServingEngine(model, params, **kwargs)


# -- typed scheduler rejections (satellite regression) ----------------------


def test_submit_result_typed_reasons():
    """FIFOScheduler.submit reports WHY it refused — queue_full vs
    draining — through a result that still behaves like the old bool."""
    sched = FIFOScheduler(SchedulerConfig(max_queue=1))
    a = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    b = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    ok = sched.submit(a)
    assert ok and bool(ok) and ok.reason is None
    full = sched.submit(b)
    assert not full and full.reason == REJECT_QUEUE_FULL
    sched.begin_drain()
    sched.take_queued()
    draining = sched.submit(b)
    assert not draining and draining.reason == REJECT_DRAINING
    # relocation of accepted work bypasses the drain gate, not the bound
    assert sched.submit(b, requeue=True)
    assert sched.depth == 1
    c = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    assert sched.submit(c, requeue=True).reason == REJECT_QUEUE_FULL


def test_engine_surfaces_typed_reject(env):
    """Engine rejections carry the SAME typed vocabulary the frontend
    uses (satellite: identical reporting across layers)."""
    eng = _engine(env, scheduler=SchedulerConfig(max_queue=0))
    out = eng.add_request(Request(prompt=[1, 2], max_new_tokens=2))
    assert out.status == REJECTED and out.finish_reason == REJECT_QUEUE_FULL
    eng2 = _engine(env)
    eng2.begin_drain()
    out2 = eng2.add_request(Request(prompt=[1, 2], max_new_tokens=2))
    assert out2.status == REJECTED and out2.finish_reason == REJECT_DRAINING
    assert eng2.draining


def test_scheduler_take_queued_and_remove():
    sched = FIFOScheduler()
    outs = [
        RequestOutput(Request(prompt=[1] * (i + 1)), arrival_time=0.0)
        for i in range(3)
    ]
    for out in outs:
        sched.submit(out)
    assert sched.pending_prefill_tokens == 1 + 2 + 3
    assert sched.queued() == outs
    gone = sched.remove(outs[1].request.request_id)
    assert gone is outs[1] and sched.depth == 2
    assert sched.remove("nope") is None
    taken = sched.take_queued()
    assert taken == [outs[0], outs[2]] and sched.depth == 0


def test_expire_retry_wait_accounting():
    """Satellite: an expired-then-retried request is observed ONCE in
    serving_queue_wait_seconds — at its eventual admission, carrying the
    CUMULATIVE wait across replicas (expiry itself never observes)."""
    reg = MetricRegistry()
    t = [0.0]
    a = FIFOScheduler(
        SchedulerConfig(max_wait=10.0), clock=lambda: t[0], registry=reg
    )
    out = RequestOutput(Request(prompt=[1, 2]), arrival_time=0.0)
    assert a.submit(out)
    t[0] = 11.0
    assert a.expire() == [out] and out.status == EXPIRED
    # the retry carries the ORIGINAL arrival to a different replica's
    # scheduler sharing the registry (the frontend passes arrival_time
    # through engine.add_request the same way)
    retry = RequestOutput(out.request, arrival_time=out.arrival_time)
    b = FIFOScheduler(clock=lambda: t[0], registry=reg)
    assert b.submit(retry)
    t[0] = 15.0
    assert b.schedule(1) == [retry]
    rows = [
        row for row in reg.snapshot()["histograms"]
        if row["name"] == "serving_queue_wait_seconds"
    ]
    assert len(rows) == 1
    assert rows[0]["count"] == 1  # not double-counted across schedulers
    assert rows[0]["sum"] == pytest.approx(15.0)  # cumulative, not 4.0


def test_engine_arrival_time_passthrough(env):
    """engine.add_request(arrival_time=) pins the record to the CLIENT's
    arrival instead of the local clock — the hook the cluster retry path
    uses to keep queue-wait telemetry cumulative across replicas."""
    _, _, _, prompts, _ = env
    eng = _engine(env, clock=lambda: 5.0)
    out = eng.add_request(
        Request(prompt=prompts[0], max_new_tokens=2), arrival_time=1.5
    )
    assert out.arrival_time == 1.5
    fresh = eng.add_request(Request(prompt=prompts[1], max_new_tokens=2))
    assert fresh.arrival_time == 5.0


# -- fault plan + replica handle -------------------------------------------


def test_fault_plan_windows():
    fp = FaultPlan(stall_at_tick=3, stall_ticks=2, reject_at_tick=1,
                   reject_ticks=1)
    assert not fp.stalled(2) and fp.stalled(3) and fp.stalled(4)
    assert not fp.stalled(5)
    assert fp.rejecting(1) and not fp.rejecting(2)


def test_replica_stall_degrades_then_recovers(env):
    _, _, _, prompts, refs = env
    h = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(stall_at_tick=1, stall_ticks=2)
    )
    fe = Frontend([h])
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    fe.step()  # tick 0: admitted
    fe.step()  # tick 1: stalled
    assert h.health == DEGRADED
    n_before = len(out.tokens)
    fe.step()  # tick 2: still stalled
    assert len(out.tokens) == n_before  # no progress while stalled
    fe.run(max_ticks=50)
    assert h.health == HEALTHY
    assert out.status == FINISHED
    np.testing.assert_array_equal(np.asarray(out.tokens), refs[0])


def test_reject_window_routes_to_peer(env):
    """A replica inside a FaultPlan admission-reject window is simply not
    routable — everything lands on the peer, nothing is lost."""
    _, _, _, prompts, refs = env
    h0 = ReplicaHandle(
        0, _engine(env),
        fault_plan=FaultPlan(reject_at_tick=0, reject_ticks=1000),
    )
    h1 = ReplicaHandle(1, _engine(env))
    fe = Frontend([h0, h1], router="rr")
    outs = [fe.submit(Request(prompt=p, max_new_tokens=4)) for p in prompts]
    fe.run(max_ticks=100)
    assert all(out.status == FINISHED for out in outs)
    assert h0.engine.metrics.finished == 0
    assert h1.engine.metrics.finished == len(prompts)


# -- routers ----------------------------------------------------------------


class _FakeReplica:
    def __init__(self, rid, load=0.0, queue_depth=0):
        self.replica_id = rid
        self._load = load
        self.queue_depth = queue_depth

    def load(self):
        return self._load


def test_round_robin_cycles():
    r = RoundRobinRouter()
    reps = [_FakeReplica(i) for i in range(3)]
    picks = [r.route([1], reps).replica_id for _ in range(6)]
    assert picks == [0, 1, 2, 0, 1, 2]
    assert r.route([1], []) is None


def test_least_loaded_ranks():
    reps = [
        _FakeReplica(0, load=3.0),
        _FakeReplica(1, load=1.0),
        _FakeReplica(2, load=1.0),
    ]
    assert least_loaded(reps).replica_id == 1  # tie -> lowest id
    assert least_loaded([]) is None


def test_prefix_route_key_alignment():
    assert prefix_route_key([1, 2, 3, 4, 5], (4, 8)) == (1, 2, 3, 4)
    # bucket == len is NOT a proper prefix (mirrors PrefixCache.lookup)
    assert prefix_route_key([1, 2, 3, 4], (4, 8)) == (1, 2, 3, 4)
    assert prefix_route_key([1, 2, 3], (4, 8)) == (1, 2, 3)
    assert prefix_route_key([1, 2, 3], None) == (1, 2, 3)


def test_prefix_router_stable_placement():
    """Consistent hashing: placement is deterministic, same-prefix
    prompts share an owner, and removing a replica moves ONLY the keys
    it owned (every other key keeps its warm cache)."""
    ids = [0, 1, 2, 3]
    r1 = PrefixAffinityRouter(ids, buckets=(4, 8))
    r2 = PrefixAffinityRouter(ids, buckets=(4, 8))
    prompts = [
        [i, i + 1, i + 2, i + 3, i + 4, i + 5] for i in range(0, 120, 3)
    ]
    owners = [r1.owner(p) for p in prompts]
    assert owners == [r2.owner(p) for p in prompts]  # deterministic
    assert len(set(owners)) > 1  # keys actually spread
    # same bucket-aligned prefix, different suffix -> same owner
    assert r1.owner([5, 6, 7, 8, 99, 98]) == r1.owner([5, 6, 7, 8, 1, 2])
    # kill replica `dead`: its keys move, every other key stays put
    dead = owners[0]
    reps = {i: _FakeReplica(i) for i in ids}
    alive = [reps[i] for i in ids if i != dead]
    for p, owner in zip(prompts, owners):
        new = r1.route(p, alive).replica_id
        if owner != dead:
            assert new == owner, "surviving replica's keys must not move"
        else:
            assert new != dead


def test_prefix_router_overload_falls_back():
    reps = [
        _FakeReplica(0, load=9.0, queue_depth=9),
        _FakeReplica(1, load=0.0, queue_depth=0),
    ]
    r = PrefixAffinityRouter([0, 1], buckets=(4,), overload_queue_depth=8)
    # find a prompt whose owner is replica 0, then overload it
    prompt = next(
        p for p in ([i, i + 1, i + 2, i + 3, i + 4] for i in range(200))
        if r.owner(p) == 0
    )
    assert r.route(prompt, reps).replica_id == 1
    assert r.fallbacks == 1


def test_make_router_unknown_policy():
    with pytest.raises(ValueError, match="unknown router"):
        make_router("zigzag", [0, 1])


# -- engine cancel / drain --------------------------------------------------


def test_engine_cancel_running_and_queued(env):
    """cancel() frees the slot mid-decode (alignment preserved), pulls
    queued requests before they ever run, and streams a terminal event."""
    _, _, _, prompts, _ = env
    eng = _engine(env, n_slots=1)
    seen = []
    a = eng.add_request(Request(prompt=prompts[0], max_new_tokens=20))
    b = eng.add_request(
        Request(prompt=prompts[1], max_new_tokens=4,
                on_token=lambda ev: seen.append(ev))
    )
    eng.step()  # a running, b queued
    assert a.status == "running"
    assert eng.cancel(b.request.request_id)  # queued cancel
    assert b.status == CANCELLED and b.finish_reason == "cancelled"
    assert seen and seen[0].token == -1 and seen[0].finish_reason == "cancelled"
    eng.step()
    assert eng.cancel(a.request.request_id, reason="deadline")  # running
    assert a.status == CANCELLED and a.finish_reason == "deadline"
    assert eng.pool.n_free == 1  # slot came back
    eng.pool.assert_slot_aligned(0)
    assert eng.metrics.cancelled == 2
    assert not eng.cancel("unknown")
    assert not eng.cancel(a.request.request_id)  # already terminal
    # the engine still serves correctly after cancels
    c = eng.add_request(Request(prompt=prompts[2], max_new_tokens=3))
    eng.run()
    assert c.status == FINISHED


# -- frontend admission control --------------------------------------------


def test_token_budget_backpressure(env):
    """Global token-budget: typed rejection past the cap, capacity
    released as requests finish."""
    _, _, _, prompts, _ = env
    fe = Frontend(
        [_engine(env)],
        config=FrontendConfig(max_inflight_tokens=20),
    )
    a = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))  # 3+8=11
    b = fe.submit(Request(prompt=prompts[4], max_new_tokens=4))  # 5+4=9
    c = fe.submit(Request(prompt=prompts[2], max_new_tokens=4))
    assert a.status != REJECTED and b.status != REJECTED
    assert c.status == REJECTED and c.finish_reason == REJECT_TOKEN_BUDGET
    fe.run(max_ticks=100)
    assert a.status == FINISHED and b.status == FINISHED
    d = fe.submit(Request(prompt=prompts[2], max_new_tokens=4))
    assert d.status != REJECTED  # reservations released
    fe.run(max_ticks=100)
    assert d.status == FINISHED


def test_per_client_concurrency_cap(env):
    _, _, _, prompts, _ = env
    fe = Frontend([_engine(env)], config=FrontendConfig(max_per_client=2))
    a = fe.submit(Request(prompt=prompts[0], max_new_tokens=4,
                          client_id="alice"))
    b = fe.submit(Request(prompt=prompts[1], max_new_tokens=4,
                          client_id="alice"))
    c = fe.submit(Request(prompt=prompts[2], max_new_tokens=4,
                          client_id="alice"))
    d = fe.submit(Request(prompt=prompts[3], max_new_tokens=4,
                          client_id="bob"))
    anon = fe.submit(Request(prompt=prompts[4], max_new_tokens=4))
    assert c.status == REJECTED and c.finish_reason == REJECT_CLIENT_LIMIT
    assert d.status != REJECTED  # other clients unaffected
    assert anon.status != REJECTED  # no client_id -> uncapped
    fe.run(max_ticks=200)
    assert all(o.status == FINISHED for o in (a, b, d, anon))
    # capacity freed: alice can submit again
    e = fe.submit(Request(prompt=prompts[2], max_new_tokens=2,
                          client_id="alice"))
    assert e.status != REJECTED


def test_priority_aging_prevents_starvation(env):
    """Priority reorders admission but never starves: under a continuous
    flood of fresh high-priority arrivals that outpaces one slot, an aged
    low-priority request still finishes; the strict-priority control
    (effectively no aging) starves it."""
    _, _, _, prompts, _ = env

    def drive(aging_seconds, ticks=60):
        t = [0.0]
        eng = _engine(env, clock=lambda: t[0], n_slots=1)
        fe = Frontend(
            [eng], clock=lambda: t[0],
            config=FrontendConfig(aging_seconds=aging_seconds),
        )
        low = fe.submit(
            Request(prompt=prompts[0], max_new_tokens=2, priority=0)
        )
        for k in range(ticks):
            t[0] += 1.0
            # two fresh priority-5 arrivals per tick >> service rate
            fe.submit(
                Request(prompt=prompts[2], max_new_tokens=2, priority=5)
            )
            fe.submit(
                Request(prompt=prompts[2], max_new_tokens=2, priority=5)
            )
            fe.step()
            if low.status == FINISHED:
                return k
        return None

    aged = drive(aging_seconds=2.0)
    assert aged is not None, "aging must rescue the low-priority request"
    starved = drive(aging_seconds=1e9)
    assert starved is None, (
        "strict priority should starve it — otherwise this test proves "
        "nothing about aging"
    )


def test_deadline_cancels_in_engine_work(env):
    """A request past its deadline is cancelled mid-decode: slot
    released, typed terminal event streamed, neighbours unharmed."""
    _, _, _, prompts, refs = env
    t = [0.0]
    eng = _engine(env, clock=lambda: t[0], n_slots=2)
    fe = Frontend([eng], clock=lambda: t[0])
    seen = []
    a = fe.submit(
        Request(prompt=prompts[0], max_new_tokens=20, deadline=5.0,
                on_token=lambda ev: seen.append(ev))
    )
    b = fe.submit(Request(prompt=prompts[1], max_new_tokens=8))
    t[0] = 1.0
    fe.step()
    assert a.status == "running"
    t[0] = 6.0
    fe.step()
    assert a.status == CANCELLED and a.finish_reason == "deadline"
    assert seen[-1].token == -1 and seen[-1].finish_reason == "deadline"
    fe.run(max_ticks=100)
    assert b.status == FINISHED
    np.testing.assert_array_equal(np.asarray(b.tokens), refs[1])
    assert eng.pool.n_free == 2
    assert fe.summary()["cancelled"] == 1
    # a pending (never-dispatched) request past deadline cancels too
    t2 = [0.0]
    eng2 = _engine(env, clock=lambda: t2[0], n_slots=1)
    fe2 = Frontend([eng2], clock=lambda: t2[0])
    busy = fe2.submit(Request(prompt=prompts[0], max_new_tokens=8))
    lazy = fe2.submit(
        Request(prompt=prompts[1], max_new_tokens=8, deadline=2.0)
    )
    t2[0] = 1.0
    fe2.step()
    t2[0] = 3.0
    fe2.step()
    assert lazy.status == CANCELLED and lazy.finish_reason == "deadline"
    fe2.run(max_ticks=100)
    assert busy.status == FINISHED


# -- exactness under failure (the headline acceptance) ----------------------


_MODES = {
    "exact": dict(prefill_buckets=None),
    "bucketed": dict(prefill_buckets=(4, 8, 16)),
    "chunked": dict(prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4),
    "spec": dict(prefill_buckets=(4, 8, 16), draft_tokens=3),
}


@pytest.mark.parametrize("mode", sorted(_MODES))
def test_crash_midflight_bitwise_exact(env, mode):
    """Acceptance: with a FaultPlan killing one replica mid-decode, every
    request completes and greedy tokens are BITWISE identical to a
    single-engine no-fault baseline — per prefill/decode mode."""
    _, _, _, prompts, _ = env
    kw = _MODES[mode]

    baseline_eng = _engine(env, **kw)
    base_outs = [
        baseline_eng.add_request(Request(prompt=p, max_new_tokens=8))
        for p in prompts
    ]
    baseline_eng.run()
    assert all(o.status == FINISHED for o in base_outs)

    h0 = ReplicaHandle(
        0, _engine(env, **kw), fault_plan=FaultPlan(crash_at_tick=3)
    )
    h1 = ReplicaHandle(1, _engine(env, **kw))
    fe = Frontend([h0, h1], router="rr")
    outs = [fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts]
    fe.run(max_ticks=400)
    assert h0.health == DEAD
    s = fe.summary()
    assert s["replica_deaths"] == 1 and s["retries"] > 0
    for i, (out, base) in enumerate(zip(outs, base_outs)):
        assert out.status == FINISHED, (
            f"request {i}: {out.status} ({out.finish_reason})"
        )
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(base.tokens),
            err_msg=f"request {i} diverged after failover ({mode})",
        )


def test_crash_midflight_exact_fused_tick(env):
    """The headline crash guarantee holds under the FUSED decode tick
    (the engine default): a replica dying between multi-token ticks is
    replayed forced-prefix on the survivor, greedy output bitwise equal
    to a no-fault fused baseline — which itself equals the per-step
    engine (serving parity suite)."""
    _, _, _, prompts, _ = env
    kw = dict(prefill_buckets=(4, 8, 16), decode_steps_per_tick=4)

    baseline_eng = _engine(env, **kw)
    base_outs = [
        baseline_eng.add_request(Request(prompt=p, max_new_tokens=16))
        for p in prompts
    ]
    baseline_eng.run()
    assert all(o.status == FINISHED for o in base_outs)

    h0 = ReplicaHandle(
        0, _engine(env, **kw), fault_plan=FaultPlan(crash_at_tick=2)
    )
    h1 = ReplicaHandle(1, _engine(env, **kw))
    fe = Frontend([h0, h1], router="rr")
    outs = [fe.submit(Request(prompt=p, max_new_tokens=16)) for p in prompts]
    fe.run(max_ticks=400)
    assert h0.health == DEAD
    s = fe.summary()
    assert s["replica_deaths"] == 1 and s["retries"] > 0
    for i, (out, base) in enumerate(zip(outs, base_outs)):
        assert out.status == FINISHED, (
            f"request {i}: {out.status} ({out.finish_reason})"
        )
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(base.tokens),
            err_msg=f"request {i} diverged after fused-tick failover",
        )


def test_crash_stream_indices_stay_contiguous(env):
    """Across a failover the client stream never re-delivers or skips:
    every request's event indices are exactly 0..n-1 in order."""
    _, _, _, prompts, refs = env
    streams = {}

    def track(ev):
        streams.setdefault(ev.request_id, []).append(ev)

    h0 = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(crash_at_tick=3)
    )
    h1 = ReplicaHandle(1, _engine(env))
    fe = Frontend([h0, h1], router="rr")
    outs = [
        fe.submit(
            Request(prompt=p, max_new_tokens=8, on_token=track)
        )
        for p in prompts
    ]
    fe.run(max_ticks=400)
    assert fe.summary()["retries"] > 0
    for out, ref in zip(outs, refs):
        events = streams[out.request.request_id]
        assert [ev.index for ev in events] == list(range(8))
        assert [ev.token for ev in events] == list(ref)
        assert events[-1].finished and not any(
            ev.finished for ev in events[:-1]
        )


def test_expiry_bounce_terminates_instead_of_livelocking(env):
    """Regression: a request whose CUMULATIVE wait already exceeds an
    engine's max_wait would expire at every re-dispatch forever (the
    retry preserves the original arrival).  Bounces count against
    retry_limit, so the request terminates EXPIRED and run()/drain()
    still halt."""
    _, _, _, prompts, _ = env
    t = [0.0]
    eng = _engine(
        env, clock=lambda: t[0], n_slots=1,
        scheduler=SchedulerConfig(max_wait=1.0),
    )
    fe = Frontend(
        [eng], clock=lambda: t[0], config=FrontendConfig(retry_limit=2)
    )
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=2))
    t[0] = 5.0  # past the engine's max_wait before first dispatch
    fe.run(max_ticks=20)
    assert out.status == EXPIRED and out.finish_reason == "max_wait"
    assert not fe.has_work()
    assert out.retries == 3  # retry_limit + the terminal bounce


def test_retry_limit_fails_loudly(env):
    _, _, _, prompts, _ = env
    h0 = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(crash_at_tick=1)
    )
    fe = Frontend([h0], config=FrontendConfig(retry_limit=0))
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    fe.run(max_ticks=20)
    assert out.status == FAILED and out.finish_reason == "retry_limit"
    assert not fe.has_work()


def test_all_replicas_dead_fails_pending(env):
    _, _, _, prompts, _ = env
    handles = [
        ReplicaHandle(
            i, _engine(env, n_slots=1),
            fault_plan=FaultPlan(crash_at_tick=i + 1),
        )
        for i in range(2)
    ]
    fe = Frontend(handles, config=FrontendConfig(retry_limit=5))
    outs = [fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts]
    fe.run(max_ticks=50)
    assert all(h.health == DEAD for h in handles)
    assert not fe.has_work()
    assert all(out.done for out in outs)
    assert any(
        out.status == FAILED
        and out.finish_reason in ("no_replica", "retry_limit")
        for out in outs
    )


# -- drain ------------------------------------------------------------------


def test_drain_terminates_and_releases(env):
    """Acceptance: drain() finishes in-flight work, re-routes the queued
    remainder, admits nothing new, and leaves every replica's CachePool
    fully released with aligned position tables."""
    _, _, _, prompts, refs = env
    engines = [_engine(env, n_slots=1) for _ in range(2)]
    fe = Frontend(
        engines, router="least",
        # deep dispatch so engine queues actually hold a remainder
        config=FrontendConfig(dispatch_queue_depth=4),
    )
    outs = [fe.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts]
    fe.step()  # fills slots and engine queues
    assert any(eng.scheduler.depth > 0 for eng in engines)
    events = fe.drain(max_ticks=300)
    assert not fe.has_work()
    assert all(out.status == FINISHED for out in outs)
    s = fe.summary()
    assert s["requeued"] > 0  # the queued remainder really re-routed
    late = fe.submit(Request(prompt=prompts[0], max_new_tokens=2))
    assert late.status == REJECTED and late.finish_reason == REJECT_DRAINING
    for eng in engines:
        assert eng.draining
        assert eng.pool.n_free == eng.pool.n_slots
        for slot in range(eng.pool.n_slots):
            eng.pool.assert_slot_aligned(slot)
    # drained output is still exact
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(ref)[: len(out.tokens)]
        )
        assert len(out.tokens) == 6
    assert any(ev.finished for ev in events)


# -- telemetry wiring -------------------------------------------------------


def test_cluster_metrics_and_router_track(env):
    """cluster_* registry series and router-track trace events appear end
    to end; the snapshot passes the exporter schema gate."""
    from tpu_parallel.obs import Tracer, validate_snapshot

    _, _, _, prompts, _ = env
    tracer = Tracer()
    h0 = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(crash_at_tick=3)
    )
    h1 = ReplicaHandle(1, _engine(env))
    fe = Frontend([h0, h1], router="rr", tracer=tracer)
    outs = [fe.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts]
    fe.run(max_ticks=300)
    assert all(out.status == FINISHED for out in outs)
    snap = fe.registry.snapshot()
    assert validate_snapshot(snap) == []
    gauges = {
        (row["name"], row["labels"].get("replica")): row["value"]
        for row in snap["gauges"]
    }
    assert ("cluster_replica_health", "0") in gauges
    assert gauges[("cluster_replica_health", "0")] == 2.0  # dead
    assert gauges[("cluster_replica_health", "1")] == 0.0  # healthy
    counters = {
        row["name"]: row["value"]
        for row in snap["counters"]
        if not row["labels"]
    }
    assert counters["cluster_replica_deaths_total"] == 1
    assert counters["cluster_retries_total"] >= 1
    names = {ev["name"] for ev in tracer.instants}
    assert {"route", "replica_death", "retry"} <= names
    assert all(
        ev["track"] == "router" for ev in tracer.instants
        if ev["name"] in ("route", "replica_death", "retry")
    )
    imb = [
        row for row in snap["histograms"]
        if row["name"] == "cluster_route_imbalance"
    ]
    assert imb and imb[0]["count"] > 0


# -- clock discipline (satellite) ------------------------------------------


def test_serving_time_flows_through_clock():
    """Tier-1 wiring of scripts/check_clock.py: no module under
    tpu_parallel/serving/ or tpu_parallel/cluster/ reads wall time
    directly — plus a self-test that the checker actually catches
    violations."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        import check_clock
    finally:
        sys.path.pop(0)
    problems = check_clock.check_paths(
        (
            os.path.join(repo, "tpu_parallel", "serving"),
            os.path.join(repo, "tpu_parallel", "cluster"),
        )
    )
    assert problems == [], "\n".join(problems)
    # the checker catches attribute calls, from-imports, and sleep —
    # while a clock DEFAULT (dependency injection) stays legal
    bad = (
        "import time\n"
        "from time import monotonic as mono\n"
        "def f():\n"
        "    a = time.time()\n"
        "    b = mono()\n"
        "    time.sleep(1)\n"
        "def ok(clock=time.monotonic):\n"
        "    return clock()\n"
    )
    found = check_clock.check_source(bad, "x.py")
    assert len(found) == 3
    assert any("time.time()" in p for p in found)
    assert any("mono()" in p for p in found)
    assert any("time.sleep()" in p for p in found)


def test_serving_no_per_slot_host_sync():
    """Tier-1 wiring of scripts/check_host_sync.py: no module under
    tpu_parallel/serving/ syncs the device inside a host loop (per-slot
    syncs are the dispatch tax the fused tick exists to kill; the one
    tick-boundary sync in the speculative host loop carries the
    ``# host-sync:`` annotation) — plus a self-test that the checker
    catches violations and honors the whitelist."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        import check_host_sync
    finally:
        sys.path.pop(0)
    problems = check_host_sync.check_paths(
        (os.path.join(repo, "tpu_parallel", "serving"),)
    )
    assert problems == [], "\n".join(problems)
    bad = (
        "import numpy as np\n"
        "def f(slots, fetch):\n"
        "    for s in slots:\n"
        "        a = np.asarray(fetch(s))\n"
        "        fetch(s).block_until_ready()\n"
        "    while slots:\n"
        "        b = np.asarray(slots.pop())  # host-sync: tick-boundary\n"
        "    c = np.asarray(fetch(0))\n"
        "def g(xs, fetch):\n"
        "    return [np.asarray(fetch(x)) for x in xs]\n"
        "def h(dev_batch):\n"
        "    return [int(t) for t in np.asarray(dev_batch)]\n"
    )
    found = check_host_sync.check_source(bad, "x.py")
    # the two for-body calls AND the per-iteration comprehension call
    # flag; the annotated while-body call, the loop-free call, and the
    # iterate-ONCE comprehension iterable stay legal
    assert len(found) == 3, found
    assert any("np.asarray" in p and ":4:" in p for p in found)
    assert any("block_until_ready" in p for p in found)
    assert any(":10:" in p for p in found)
    # the whitelist annotation counts anywhere in a wrapped call's span
    # (black parks the trailing comment on the closing-paren line)
    wrapped = (
        "import numpy as np\n"
        "def f(slots, fetch):\n"
        "    while slots:\n"
        "        b = np.asarray(\n"
        "            fetch(slots.pop())\n"
        "        )  # host-sync: tick-boundary\n"
    )
    assert check_host_sync.check_source(wrapped, "x.py") == []
    # a typo'd path must fail loudly, never walk zero files and pass
    with pytest.raises(FileNotFoundError):
        check_host_sync.check_paths((os.path.join(repo, "no_such_dir"),))


# -- prefix affinity wins (slow) -------------------------------------------


@pytest.mark.slow
def test_prefix_affinity_beats_round_robin(env):
    """Acceptance (slow lane): on a repeated-prefix workload, prefix-
    affinity routing's aggregate prefix-cache hit rate beats round-robin
    (group placement is sticky instead of scattered)."""
    import random

    cfg, model, params, _, _ = env
    rng = jax.random.PRNGKey(11)
    rnd = random.Random(0)
    groups = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, g), (8,), 1, cfg.vocab_size
            )
        )]
        for g in range(3)
    ]
    prompts = []
    for i in range(18):
        hdr = groups[rnd.randrange(3)]
        sfx = [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 100 + i), (3 + i % 4,), 1,
                cfg.vocab_size,
            )
        )]
        prompts.append(hdr + sfx)

    def drive(policy):
        engines = [
            ServingEngine(
                model, params, n_slots=2,
                scheduler=SchedulerConfig(max_prefills_per_tick=1),
                prefill_buckets=(8, 16), prefix_cache_size=4,
            )
            for _ in range(3)
        ]
        fe = Frontend(engines, router=policy)
        outs = []
        for p in prompts:  # one arrival per tick: queues stay shallow
            outs.append(fe.submit(Request(prompt=p, max_new_tokens=4)))
            fe.step()
        fe.run(max_ticks=400)
        assert all(out.status == FINISHED for out in outs)
        return fe.prefix_hit_rate()

    affinity = drive("prefix")
    rr = drive("rr")
    assert affinity is not None and rr is not None
    assert affinity > rr, (affinity, rr)
