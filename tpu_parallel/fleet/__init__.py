"""Multi-process serving fleet: N independent daemon processes behind
one wire-level router (docs/14_fleet.md).

Everything the stack does in-process — prefix-affinity routing,
breaker-guarded health, KV migration, forced-prefix replay — exists
here a second time ACROSS process and host boundaries, built from the
same primitives: the router reuses the cluster's consistent-hash ring
over daemon addresses, peer health reuses the replica breaker's state
vocabulary, remote KV migration ships the CRC-checksummed
:class:`KVPrefixExport` through the ``serving/kv_wire.py`` codec, and
cross-host handoff replays a dead host's streams onto survivors via
the same forced-prefix mechanism daemon crash recovery uses — so
greedy continuations stay bitwise across a host death.

- :mod:`tpu_parallel.fleet.peers` — peer health (HEALTHY → DEGRADED →
  DEAD with backoff re-probe) on the injectable clock.
- :mod:`tpu_parallel.fleet.router` — the transport-agnostic router
  core: typed admission, retry-with-exclusion, the fleet-wide dedupe
  ledger, handoff, and KV warm-start/drain-forward orchestration.
- :mod:`tpu_parallel.fleet.http` — the urllib transport + the
  client-facing server re-serving the daemon's exact HTTP/SSE
  contract.
"""

from tpu_parallel.fleet.http import FleetHTTPServer, HTTPFleetTransport
from tpu_parallel.fleet.peers import (
    DEAD,
    DEGRADED,
    HEALTHY,
    PeerPolicy,
    PeerSet,
    PeerState,
)
from tpu_parallel.fleet.roles import (
    PHASE_DECODE,
    REJECT_ROLE,
    ROLE_DECODE,
    ROLE_MIXED,
    ROLE_PREFILL,
    ROLES,
    can_decode,
    can_prefill,
    disaggregated,
    validate_role,
)
from tpu_parallel.fleet.router import (
    FLEET_TRACK,
    REJECT_HANDOFFS,
    REJECT_NO_PEER,
    FleetRouter,
    FleetTransport,
    TransportError,
)

__all__ = [
    "HEALTHY",
    "DEGRADED",
    "DEAD",
    "PeerPolicy",
    "PeerState",
    "PeerSet",
    "FLEET_TRACK",
    "REJECT_NO_PEER",
    "REJECT_HANDOFFS",
    "FleetRouter",
    "FleetTransport",
    "TransportError",
    "HTTPFleetTransport",
    "FleetHTTPServer",
    "ROLE_PREFILL",
    "ROLE_DECODE",
    "ROLE_MIXED",
    "ROLES",
    "REJECT_ROLE",
    "PHASE_DECODE",
    "validate_role",
    "can_prefill",
    "can_decode",
    "disaggregated",
]
