"""Length-prefixed binary wire codec for :class:`KVPrefixExport`.

PR 15 made the export a self-verifying exchange unit (one CRC32 per
block, recomputed before any import lands).  This module makes it a
WIRE format: ``encode_export`` flattens one export into a single frame
of bytes, ``decode_export`` rebuilds it bitwise, and concatenated
frames (``encode_exports`` / ``decode_exports``) are the body of the
fleet's ``/v1/kv/export`` → ``/v1/kv/import`` exchange
(docs/14_fleet.md).

Frame layout (all integers big-endian)::

    magic   b"KVW1"                       4 bytes
    hlen    uint32  header length         4 bytes
    hcrc    uint32  CRC32 of header       4 bytes
    header  canonical JSON (utf-8)        hlen bytes
    payload leaf arrays, C-order bytes    sum(leaf nbytes)

The header carries everything except the raw K/V bytes — tokens,
block geometry, ``weights_version``, the exporter's ``meta`` shape
signature, the per-block checksums, and each leaf's dtype/shape (which
is what makes the payload self-describing: leaf byte extents are
derived, never trusted from a length field that could disagree).

Decoding REFUSES, never guesses: every way a frame can be damaged maps
to a typed :class:`WireFormatError` reason (``truncated``, ``magic``,
``header_crc``, ``header_schema``, ``integrity``).  A bit flipped in
the payload trips the per-block CRC (``integrity``); a bit flipped in
the header trips ``hcrc`` before the JSON is even parsed — so version
skew and shape compatibility are still judged by
:meth:`ServingEngine.import_prefix` on exactly the values the exporter
wrote, and corrupt bytes never serve (the importer recomputes from
tokens instead).

The codec is pure bytes-in/bytes-out; only the file helpers at the
bottom touch the filesystem, and they go through the
``daemon.iofaults`` read gate so the seeded-rot soak covers blobs at
rest the same way it covers the journal.
"""

from __future__ import annotations

import json
import math
import struct
import zlib
from typing import List, Tuple

import numpy as np

from tpu_parallel.serving.kv_hierarchy import KVPrefixExport

MAGIC = b"KVW1"
_HEADER_STRUCT = struct.Struct(">II")  # hlen, hcrc
_FRAME_OVERHEAD = len(MAGIC) + _HEADER_STRUCT.size

# a header is small (tokens + shapes); anything claiming more is damage,
# not data — refuse before allocating
MAX_HEADER_BYTES = 1 << 24

WIRE_TRUNCATED = "truncated"
WIRE_MAGIC = "magic"
WIRE_HEADER_CRC = "header_crc"
WIRE_HEADER_SCHEMA = "header_schema"
WIRE_INTEGRITY = "integrity"
WIRE_SEGMENT = "segment"  # chunk-stream framing damage (see below)

WIRE_REASONS = (
    WIRE_TRUNCATED,
    WIRE_MAGIC,
    WIRE_HEADER_CRC,
    WIRE_HEADER_SCHEMA,
    WIRE_INTEGRITY,
    WIRE_SEGMENT,
)

# -- streaming-chunk framing (the disaggregation hot path) -------------------
#
# A KV handoff can be far larger than a sane single message, so exports
# over ``max_wire_bytes`` ship as a CHUNK STREAM: the concatenated-frame
# body is sliced into self-checksummed segments, closed by a terminal
# segment that carries the whole-body CRC.  Each segment::
#
#     smagic  b"KVC1"                      4 bytes
#     seq     uint32 (0-based)             4 bytes
#     slen    uint32 payload length        4 bytes
#     scrc    uint32 CRC32(payload)        4 bytes
#     payload slen bytes of the frame body
#
# The terminal segment has ``slen == 0``, ``seq == n_data_segments`` and
# ``scrc == CRC32(full body)``.  The receiver imports nothing from a
# stream it cannot finish verifying PER FRAME: whole KVW1 frames that
# complete inside the received prefix may land early (each frame is
# already self-verifying — Mooncake-style overlap), but a missing,
# reordered, damaged or unterminated segment is a typed ``segment``
# refusal and the partially-received remainder never lands — no
# half-imported prefix.

CHUNK_MAGIC = b"KVC1"
_SEGMENT_STRUCT = struct.Struct(">III")  # seq, slen, scrc
SEGMENT_OVERHEAD = len(CHUNK_MAGIC) + _SEGMENT_STRUCT.size

# default per-message bound for chunked shipment: large enough that a
# warm-start blob rarely chunks, small enough that a handoff's transfer
# pipelines instead of arriving as one multi-hundred-MB message
DEFAULT_MAX_WIRE_BYTES = 1 << 20


class WireFormatError(ValueError):
    """A frame that cannot be decoded — carries the typed ``reason``
    (one of :data:`WIRE_REASONS`) the refusing side reports, so the
    import endpoint's 400 and the fleet's ``fleet_kv_wire_refusals``
    counter speak the same vocabulary as the migration verdicts."""

    def __init__(self, reason: str, detail: str):
        assert reason in WIRE_REASONS, reason
        super().__init__(f"{reason}: {detail}")
        self.reason = reason
        self.detail = detail


def _dtype(name: str) -> np.dtype:
    """Resolve a dtype name recorded at encode time.  Plain numpy names
    resolve directly; the ml_dtypes extensions jax caches use
    (bfloat16, float8 variants) resolve through the registered scalar
    types."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except (AttributeError, TypeError):
            raise WireFormatError(
                WIRE_HEADER_SCHEMA, f"unknown leaf dtype {name!r}"
            ) from None


def _tuplize(obj):
    """JSON loses tuple-ness; ``meta`` equality at import compares
    against the pool's tuple-of-tuples signature, so rebuild it."""
    if isinstance(obj, list):
        return tuple(_tuplize(x) for x in obj)
    return obj


def encode_export(export: KVPrefixExport) -> bytes:
    """One export → one frame of bytes (see the module docstring for
    the layout).  Leaves are shipped as C-order raw bytes; the header's
    per-leaf dtype/shape entries are what decode uses to carve the
    payload back up, and the canonical-JSON header keeps equal exports
    byte-identical on the wire."""
    leaves = [np.ascontiguousarray(leaf) for leaf in export.leaves]
    header = {
        "tokens": [int(t) for t in export.tokens],
        "length": int(export.length),
        "block_tokens": int(export.block_tokens),
        "weights_version": str(export.weights_version),
        "meta": export.meta,
        "checksums": [int(c) for c in export.checksums],
        "leaves": [
            {"dtype": str(leaf.dtype), "shape": list(leaf.shape)}
            for leaf in leaves
        ],
    }
    hbytes = json.dumps(
        header, sort_keys=True, separators=(",", ":"), default=list
    ).encode("utf-8")
    frame = [
        MAGIC,
        _HEADER_STRUCT.pack(len(hbytes), zlib.crc32(hbytes) & 0xFFFFFFFF),
        hbytes,
    ]
    frame.extend(leaf.tobytes(order="C") for leaf in leaves)
    return b"".join(frame)


def _decode_frame(
    buf: bytes, off: int, verify: bool
) -> Tuple[KVPrefixExport, int]:
    """Decode one frame starting at ``off``; returns the export and the
    offset just past it.  Raises :class:`WireFormatError` — typed,
    never a stray struct/json/numpy exception."""
    if len(buf) - off < _FRAME_OVERHEAD:
        raise WireFormatError(
            WIRE_TRUNCATED,
            f"{len(buf) - off} bytes at offset {off}, "
            f"frame prelude needs {_FRAME_OVERHEAD}",
        )
    if buf[off:off + len(MAGIC)] != MAGIC:
        raise WireFormatError(
            WIRE_MAGIC,
            f"bad magic {buf[off:off + len(MAGIC)]!r} at offset {off}",
        )
    hlen, hcrc = _HEADER_STRUCT.unpack_from(buf, off + len(MAGIC))
    if hlen > MAX_HEADER_BYTES:
        raise WireFormatError(
            WIRE_HEADER_SCHEMA, f"header claims {hlen} bytes"
        )
    hstart = off + _FRAME_OVERHEAD
    if len(buf) - hstart < hlen:
        raise WireFormatError(
            WIRE_TRUNCATED,
            f"header needs {hlen} bytes, {len(buf) - hstart} remain",
        )
    hbytes = buf[hstart:hstart + hlen]
    if (zlib.crc32(hbytes) & 0xFFFFFFFF) != hcrc:
        raise WireFormatError(
            WIRE_HEADER_CRC, "header CRC mismatch (damaged in transit)"
        )
    try:
        header = json.loads(hbytes.decode("utf-8"))
        tokens = tuple(int(t) for t in header["tokens"])
        length = int(header["length"])
        block_tokens = int(header["block_tokens"])
        weights_version = str(header["weights_version"])
        meta = _tuplize(header["meta"])
        checksums = tuple(int(c) for c in header["checksums"])
        leaf_specs = []
        for spec in header["leaves"]:
            shape = tuple(int(d) for d in spec["shape"])
            if any(d < 0 for d in shape):
                # a negative dim would make the extent arithmetic lie
                # (count<0 reads the whole buffer, pos walks backwards)
                raise WireFormatError(
                    WIRE_HEADER_SCHEMA, f"negative leaf dim in {shape}"
                )
            leaf_specs.append((_dtype(spec["dtype"]), shape))
    except WireFormatError:
        raise
    except (KeyError, TypeError, ValueError) as exc:
        raise WireFormatError(
            WIRE_HEADER_SCHEMA, f"malformed header: {exc}"
        ) from None
    pos = hstart + hlen
    leaves = []
    for dtype, shape in leaf_specs:
        # Python-int arithmetic: a huge claimed dim must overflow into
        # "bigger than the buffer" (truncated), never wrap negative
        count = math.prod(shape)
        nbytes = dtype.itemsize * count
        if nbytes > len(buf) - pos:
            raise WireFormatError(
                WIRE_TRUNCATED,
                f"leaf needs {nbytes} bytes, {len(buf) - pos} remain",
            )
        try:
            arr = np.frombuffer(buf, dtype=dtype, count=count, offset=pos)
            leaves.append(arr.reshape(shape).copy())
        except (ValueError, TypeError) as exc:
            raise WireFormatError(
                WIRE_HEADER_SCHEMA, f"leaf does not carve: {exc}"
            ) from None
        pos += nbytes
    export = KVPrefixExport(
        tokens=tokens,
        length=length,
        block_tokens=block_tokens,
        weights_version=weights_version,
        meta=meta,
        leaves=tuple(leaves),
        checksums=checksums,
    )
    if verify and not export.verified():
        raise WireFormatError(
            WIRE_INTEGRITY,
            "per-block CRC mismatch — payload damaged in transit",
        )
    return export, pos


def decode_export(buf: bytes, *, verify: bool = True) -> KVPrefixExport:
    """Decode exactly one frame; trailing bytes are damage, not data.
    ``verify=True`` (the default) recomputes the per-block CRCs so
    corrupt payloads refuse HERE — importers may pass ``verify=False``
    when they run the same check themselves via
    :meth:`ServingEngine.import_prefix`."""
    export, end = _decode_frame(buf, 0, verify)
    if end != len(buf):
        raise WireFormatError(
            WIRE_TRUNCATED,
            f"{len(buf) - end} trailing bytes after one frame",
        )
    return export


def encode_exports(exports) -> bytes:
    """Concatenated frames — the ``/v1/kv/export`` response body.  An
    empty list is an empty body (a donor with nothing hot is a valid
    answer, not an error)."""
    return b"".join(encode_export(e) for e in exports)


def decode_exports(
    buf: bytes, *, verify: bool = True
) -> List[KVPrefixExport]:
    """Decode a stream of concatenated frames until the buffer is
    exactly consumed.  Any damage — mid-frame truncation included —
    refuses the WHOLE stream: a partial import would leave the receiver
    believing it warm-started chains it only half holds."""
    out: List[KVPrefixExport] = []
    off = 0
    while off < len(buf):
        export, off = _decode_frame(buf, off, verify)
        out.append(export)
    return out


def _segment(seq: int, payload: bytes) -> bytes:
    return b"".join((
        CHUNK_MAGIC,
        _SEGMENT_STRUCT.pack(
            seq, len(payload), zlib.crc32(payload) & 0xFFFFFFFF
        ),
        payload,
    ))


def encode_export_chunks(
    exports, *, max_wire_bytes: int = DEFAULT_MAX_WIRE_BYTES
) -> List[bytes]:
    """Slice a stream of exports into bounded, self-checksummed chunk
    segments (see the layout comment above).  Always ends with the
    terminal segment — even an empty export list ships as one terminal
    (so the receiver can tell "nothing hot" from "transfer died").
    Concatenating the returned segments is a valid single-message body;
    sending them one write at a time is the streaming hot path."""
    return chunk_body(encode_exports(exports), max_wire_bytes=max_wire_bytes)


def chunk_body(
    body: bytes, *, max_wire_bytes: int = DEFAULT_MAX_WIRE_BYTES
) -> List[bytes]:
    """Chunk an ALREADY-ENCODED frame-stream body — the router's relay
    leg, which holds the donor's encoded bytes and must not decode K/V
    it merely forwards.  Same segment layout and terminal as
    :func:`encode_export_chunks`."""
    if max_wire_bytes < 1:
        raise ValueError(f"max_wire_bytes={max_wire_bytes} < 1")
    segments = [
        _segment(seq, body[off:off + max_wire_bytes])
        for seq, off in enumerate(range(0, len(body), max_wire_bytes))
    ]
    terminal = b"".join((
        CHUNK_MAGIC,
        _SEGMENT_STRUCT.pack(
            len(segments), 0, zlib.crc32(body) & 0xFFFFFFFF
        ),
    ))
    segments.append(terminal)
    return segments


def segment_claimed_length(prelude: bytes) -> int:
    """Payload length a segment prelude claims — the incremental
    receiver's read-ahead (how many payload bytes to pull off the
    socket before feeding).  Typed ``segment`` refusal on a short
    prelude or wrong magic, so a receiver never sizes a read from
    garbage."""
    if len(prelude) < SEGMENT_OVERHEAD:
        raise WireFormatError(
            WIRE_SEGMENT,
            f"prelude of {len(prelude)} bytes, needs {SEGMENT_OVERHEAD}",
        )
    if prelude[: len(CHUNK_MAGIC)] != CHUNK_MAGIC:
        raise WireFormatError(
            WIRE_SEGMENT,
            f"bad segment magic {prelude[:len(CHUNK_MAGIC)]!r}",
        )
    _seq, slen, _scrc = _SEGMENT_STRUCT.unpack_from(
        prelude, len(CHUNK_MAGIC)
    )
    return slen


def is_chunk_stream(buf: bytes) -> bool:
    """Whether a body starts as a chunk stream (KVC1) rather than a
    bare frame stream (KVW1) — the import endpoint's dispatch test."""
    return buf[: len(CHUNK_MAGIC)] == CHUNK_MAGIC


class ChunkReassembler:
    """Rebuild a chunk stream segment by segment, surfacing whole
    frames EARLY (``drain``) while refusing damage typed.

    Feed order is the wire order; every damage shape — wrong magic,
    out-of-order ``seq``, payload CRC mismatch, bytes after the
    terminal, or a final body whose whole-stream CRC disagrees — raises
    :class:`WireFormatError` with reason ``segment`` and poisons the
    reassembler (further feeds refuse).  ``drain`` decodes any frames
    that are COMPLETE in the verified prefix received so far; a frame
    still straddling the incoming edge stays buffered.  A receiver that
    lands drained frames as they appear and treats any raised refusal
    as "stop, import nothing further" can never half-import a prefix:
    frames are atomic and each one re-verifies its own per-block CRCs.
    """

    def __init__(self, *, verify: bool = True):
        self.verify = verify
        self._buf = bytearray()
        self._next_seq = 0
        self._decoded_off = 0  # bytes already returned via drain()
        self._finished = False
        self._failed = False

    @property
    def finished(self) -> bool:
        """True once the terminal segment verified the whole body."""
        return self._finished

    def _fail(self, detail: str) -> "WireFormatError":
        self._failed = True
        return WireFormatError(WIRE_SEGMENT, detail)

    def feed(self, segment: bytes) -> None:
        """Fold one wire segment in.  Typed ``segment`` refusal on any
        framing damage; the terminal segment closes and verifies the
        stream."""
        if self._failed:
            raise self._fail("stream already refused")
        if self._finished:
            raise self._fail("segment after terminal")
        if len(segment) < SEGMENT_OVERHEAD:
            raise self._fail(
                f"segment of {len(segment)} bytes, prelude needs "
                f"{SEGMENT_OVERHEAD}"
            )
        if segment[: len(CHUNK_MAGIC)] != CHUNK_MAGIC:
            raise self._fail(
                f"bad segment magic {segment[:len(CHUNK_MAGIC)]!r}"
            )
        seq, slen, scrc = _SEGMENT_STRUCT.unpack_from(
            segment, len(CHUNK_MAGIC)
        )
        payload = segment[SEGMENT_OVERHEAD:]
        if seq != self._next_seq:
            raise self._fail(
                f"segment seq {seq}, expected {self._next_seq} "
                "(lost or reordered in transit)"
            )
        if slen == 0:
            # terminal: scrc covers the WHOLE reassembled body
            if payload:
                raise self._fail(
                    f"{len(payload)} bytes after the terminal segment"
                )
            if (zlib.crc32(bytes(self._buf)) & 0xFFFFFFFF) != scrc:
                raise self._fail(
                    "whole-stream CRC mismatch at terminal"
                )
            self._finished = True
            return
        if len(payload) != slen:
            raise self._fail(
                f"segment claims {slen} payload bytes, "
                f"{len(payload)} present"
            )
        if (zlib.crc32(payload) & 0xFFFFFFFF) != scrc:
            raise self._fail("segment CRC mismatch (damaged in transit)")
        self._buf.extend(payload)
        self._next_seq += 1

    def drain(self) -> List[KVPrefixExport]:
        """Decode every frame COMPLETE in the verified bytes received
        so far and not yet returned — the early-overlap surface: the
        importer lands these while later segments are still in flight.
        Frame-level damage refuses typed exactly as
        :func:`decode_exports` would."""
        out: List[KVPrefixExport] = []
        buf = bytes(self._buf)
        while self._decoded_off < len(buf):
            try:
                export, end = _decode_frame(
                    buf, self._decoded_off, self.verify
                )
            except WireFormatError as exc:
                if exc.reason == WIRE_TRUNCATED and not self._finished:
                    break  # frame straddles the incoming edge: wait
                self._failed = True
                raise
            out.append(export)
            self._decoded_off = end
        if self._finished and self._decoded_off != len(buf):
            raise self._fail(
                f"{len(buf) - self._decoded_off} trailing bytes after "
                "the last whole frame"
            )
        return out

    def close(self) -> None:
        """Assert the stream terminated — call when the sender's
        connection ends.  An unterminated stream (the mid-transfer
        death case) is a typed ``segment`` refusal here, so the caller
        records it instead of mistaking the silence for success."""
        if self._failed:
            raise self._fail("stream already refused")
        if not self._finished:
            raise self._fail(
                f"stream ended after {self._next_seq} segment(s) "
                "without a terminal"
            )


def decode_export_chunks(
    buf: bytes, *, verify: bool = True
) -> List[KVPrefixExport]:
    """One-shot decode of a concatenated chunk-stream body (the
    non-streaming receiver).  Walks segment framing first, then the
    frames — every damage shape is the same typed refusal the
    incremental :class:`ChunkReassembler` raises."""
    asm = ChunkReassembler(verify=verify)
    out: List[KVPrefixExport] = []
    off = 0
    while off < len(buf) and not asm.finished:
        if len(buf) - off < SEGMENT_OVERHEAD:
            raise WireFormatError(
                WIRE_SEGMENT,
                f"{len(buf) - off} bytes at offset {off}, segment "
                f"prelude needs {SEGMENT_OVERHEAD}",
            )
        _seq, slen, _scrc = _SEGMENT_STRUCT.unpack_from(
            buf, off + len(CHUNK_MAGIC)
        )
        end = off + SEGMENT_OVERHEAD + slen
        if end > len(buf):
            raise WireFormatError(
                WIRE_SEGMENT,
                f"segment claims {slen} payload bytes, "
                f"{len(buf) - off - SEGMENT_OVERHEAD} remain",
            )
        asm.feed(buf[off:end])
        out.extend(asm.drain())
        off = end
    if off != len(buf):
        raise WireFormatError(
            WIRE_SEGMENT, f"{len(buf) - off} bytes after the terminal"
        )
    asm.close()
    return out


def write_export_file(path: str, exports) -> str:
    """Spill a stream of exports to ``path`` (the bench's corpus /
    corrupt-injection legs).  Plain binary write — durability barriers
    are the journal's business, not a bench artifact's."""
    from tpu_parallel.daemon import iofaults

    with iofaults.open_file(path, "wb") as fh:
        fh.write(encode_exports(exports))
    return path


def read_export_file(
    path: str, *, verify: bool = True
) -> List[KVPrefixExport]:
    """Read a spilled stream back through the ``iofaults`` read gate —
    an armed flip plan rots the blob exactly as it would the journal,
    and the typed refusal surfaces here instead of garbage K/V."""
    from tpu_parallel.daemon import iofaults

    return decode_exports(iofaults.read_bytes(path), verify=verify)
