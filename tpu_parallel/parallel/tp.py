"""Megatron-style tensor parallelism over a mesh axis.

No reference capability exists for TP (SURVEY.md §2.2: "Absent" — the
reference's ``param_sharding.py`` is ZeRO-3, which gathers full weights before
compute).  This module is designed from scratch for the BASELINE.json config-3
target: 1-D tensor parallel transformer layers on a ``model`` mesh axis,
composable with DP/FSDP on ``data`` and pipeline stages on ``pipe``.

Design (shard_map idiom — every function here runs per-device inside a
``shard_map`` region):

- :class:`ModuleShard` makes any inner module hold *per-device* parameters on
  one mesh axis: params get a stacked leading axis tagged ``nn.Partitioned``
  (global shape ``[axis_size, ...]``, local ``[1, ...]``), and the init RNG is
  folded over the axis so every device draws an independent slice.  This one
  wrapper implements both TP weight slicing and (in ``parallel.pp``) per-stage
  pipeline weights.
- :class:`TPDense` builds column-parallel (``full -> sharded`` activations)
  and row-parallel (``sharded -> full`` via one ``psum``) projections on top.
  A column -> nonlinearity -> row pair is the Megatron f/g conjugate pattern:
  exactly one all-reduce per MLP block on the forward pass, one on the
  backward.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.core.rng import fold_rng_over_axis

Pytree = Any


def axis_size_or_none(axis_name: str):
    """Size of a bound mesh axis, or ``None`` outside any shard_map binding it.

    Lets the TP layers degrade to plain dense compute when the model runs
    without a mesh (single-device inference, abstract param counting) — the
    structural-TP design means the same module definition must work in both
    worlds.  Note the *parameter tree differs* between the two: under a mesh,
    weights are ModuleShard-stacked ``nn.Partitioned``; without one they are
    plain Dense params.  To reuse mesh-trained checkpoints on one device,
    load them under a size-1 mesh instead.
    """
    try:
        return lax.psum(1, axis_name)
    except NameError:
        return None


def stack_params(
    params: Pytree, axis_name: str, *, axis: int = 0, mask_except: Optional[int] = None
) -> Pytree:
    """Add a size-1 leading axis tagged as partitioned over ``axis_name``.

    The global (unsharded) view of such a parameter is ``[axis_size, ...]`` —
    device i owns slice i.  ``mask_except`` zeroes the value on every device
    except one (used e.g. to keep a bias on a single TP rank so the
    post-``psum`` sum adds it exactly once).
    """

    def _stack(x):
        if isinstance(x, nn.Partitioned):
            value, names = x.value, x.names
        else:
            value, names = x, (None,) * x.ndim
        if mask_except is not None:
            axis_index = lax.axis_index(axis_name)
            value = jnp.where(axis_index == mask_except, value, jnp.zeros_like(value))
        value = jnp.expand_dims(value, axis)
        names = names[:axis] + (axis_name,) + names[axis:]
        return nn.Partitioned(value, names=names)

    return jax.tree_util.tree_map(
        _stack, params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


def unstack_params(params: Pytree, axis_name: str) -> Pytree:
    """Inverse of :func:`stack_params`: drop the stacked axis for compute."""

    def _unstack(x):
        if isinstance(x, nn.Partitioned) and axis_name in x.names:
            axis = x.names.index(axis_name)
            value = x.value.squeeze(axis)
            names = tuple(n for i, n in enumerate(x.names) if i != axis)
            if any(n is not None for n in names):
                return nn.Partitioned(value, names)
            return value
        return x

    return jax.tree_util.tree_map(
        _unstack, params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


class ModuleShard(nn.Module):
    """Give the wrapped module independent per-device parameters on one axis.

    ``module_fn`` constructs the inner module (called lazily so the wrapper is
    cheap to instantiate in lists/scans).  During init the params RNG is
    folded over ``axis_name`` — each device initializes its own shard; during
    apply the stacked axis is stripped before the inner module sees params.
    """

    module_fn: Callable[[], nn.Module]
    axis_name: str
    mask_except: Optional[int] = None

    @nn.compact
    def __call__(self, *args, **kwargs):
        if axis_size_or_none(self.axis_name) is None:
            # No mesh axis bound: plain single-copy module.
            return self.module_fn(name="sharded")(*args, **kwargs)
        if self.is_initializing():
            # Decorrelate per-device init draws.
            rng = self.scope.rngs["params"]
            self.scope.rngs["params"] = rng.replace(
                rng=fold_rng_over_axis(rng.rng, self.axis_name)
            )
        mapped = nn.map_variables(
            self.module_fn,
            trans_in_fn=functools.partial(unstack_params, axis_name=self.axis_name),
            trans_out_fn=functools.partial(
                stack_params, axis_name=self.axis_name, mask_except=self.mask_except
            ),
            mapped_collections="params",
            mutable=True,
        )
        return mapped(name="sharded")(*args, **kwargs)


def split_over_axis(x: jax.Array, axis_name: str, axis: int = -1) -> jax.Array:
    """Keep only this device's slice of ``x`` along ``axis`` (free: a slice)."""
    axis_size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    if x.shape[axis] % axis_size != 0:
        raise ValueError(
            f"cannot split axis of size {x.shape[axis]} evenly over "
            f"{axis_name}; remainder features would be silently dropped"
        )
    slice_size = x.shape[axis] // axis_size
    return lax.dynamic_slice_in_dim(x, idx * slice_size, slice_size, axis=axis)


class TPDense(nn.Module):
    """Tensor-parallel Dense over ``axis_name``.

    styles:
      - ``"column"``: input replicated, output feature-sharded (each device
        computes ``features // tp`` outputs).  Set ``gather_output=True`` to
        all-gather the result back to full features (e.g. for an lm_head).
      - ``"row"``: input feature-sharded (``split_input=True`` slices a
        replicated input instead), output full features via one ``psum``.
        The bias is a plain replicated parameter added *after* the psum, so
        it contributes exactly once regardless of tp degree.

    ``features`` is always the *global* output feature count.
    """

    features: int
    axis_name: str = "model"
    style: str = "column"
    use_bias: bool = True
    gather_output: bool = False
    split_input: bool = False
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        tp_size = axis_size_or_none(self.axis_name)
        if tp_size is None:
            # No mesh: ordinary Dense with the full feature count, laid out
            # exactly like the mesh path (same scopes, row bias outside the
            # shard) so ``export_single_device_params`` round-trips.
            y = nn.Dense(
                features=self.features,
                use_bias=self.use_bias and self.style == "column",
                dtype=self.dtype,
                kernel_init=self.kernel_init,
                bias_init=self.bias_init,
                name="shard",
            )(x)
            if self.style == "row" and self.use_bias:
                bias = self.param("bias", self.bias_init, (self.features,))
                y = y + jnp.asarray(bias, y.dtype)
            return y
        if self.style == "column":
            if self.features % tp_size != 0:
                raise ValueError(
                    f"column-parallel features={self.features} not divisible by "
                    f"tp={tp_size}"
                )
            dense_fn = functools.partial(
                nn.Dense,
                features=self.features // tp_size,
                use_bias=self.use_bias,
                dtype=self.dtype,
                kernel_init=self.kernel_init,
            )
            y = ModuleShard(dense_fn, axis_name=self.axis_name, name="shard")(x)
            if self.gather_output:
                with jax.named_scope("tp_col_all_gather"):
                    y = lax.all_gather(y, self.axis_name, axis=-1, tiled=True)
            return y
        elif self.style == "row":
            if self.split_input:
                x = split_over_axis(x, self.axis_name, axis=-1)

            # Each shard sees fan_in/tp, so a variance-scaling init (lecun/he)
            # would come out sqrt(tp) too wide and the psum of tp shards would
            # start with tp-times the dense output variance.  Rescale to the
            # global fan-in so init statistics are tp-degree-invariant.
            def row_kernel_init(key, shape, dtype=jnp.float32):
                return self.kernel_init(key, shape, dtype) * (
                    1.0 / jnp.sqrt(tp_size).astype(dtype)
                )

            dense_fn = functools.partial(
                nn.Dense,
                features=self.features,
                use_bias=False,
                dtype=self.dtype,
                kernel_init=row_kernel_init,
            )
            y = ModuleShard(dense_fn, axis_name=self.axis_name, name="shard")(x)
            with jax.named_scope("tp_row_psum"):
                y = lax.psum(y, self.axis_name)
            if self.use_bias:
                bias = self.param("bias", self.bias_init, (self.features,))
                y = y + jnp.asarray(bias, y.dtype)
            return y
        raise ValueError(f"unknown TPDense style: {self.style!r}")


def export_single_device_params(
    params: Pytree, fsdp_axes: Sequence[str] = ("data",)
) -> Pytree:
    """Convert mesh-trained params to the mesh-free module layout.

    Bridges the two parameter layouts of the structural-TP design (see
    :func:`axis_size_or_none`): unboxes ``nn.Partitioned`` leaves, squeezes
    stacked per-device axes of global size 1, and collapses the ModuleShard
    ``sharded`` scope so the tree matches what the same model produces with
    no mesh axis bound.  Use it to run single-device inference (e.g.
    ``models.generate``) on a state trained under a DP/FSDP mesh.

    ``fsdp_axes`` names the mesh axes used for FSDP-style slicing of REAL
    parameter dims (``fsdp.shard_params``): outside shard_map the global
    array already holds the full weight, so those names are simply dropped
    — even on a leading dim (the embedding's vocab dim is dim 0).

    Raises if a parameter is genuinely split over a >1 mesh axis (tp or
    pipe degree > 1, i.e. a stacked ModuleShard device axis) — such weights
    live divided across module scopes; run inference under the mesh instead
    of exporting.
    """

    def unbox(x):
        if isinstance(x, nn.Partitioned):
            value, names = x.value, x.names
            for i in reversed(range(len(names))):
                if names[i] is None:
                    continue
                if names[i] in fsdp_axes:
                    # FSDP shard of a real dim: global value is already the
                    # full weight — drop the name, keep the dim
                    continue
                if value.shape[i] == 1:
                    value = jnp.squeeze(value, i)
                elif i == 0:  # stacked ModuleShard axis with real tp/pipe degree
                    raise ValueError(
                        f"parameter is split over mesh axis {names[i]!r} "
                        f"(size {value.shape[i]}); export requires tp/pipe "
                        "degree 1 — run inference under the mesh instead. "
                        f"(If {names[i]!r} is a RENAMED data axis used for "
                        "FSDP, pass fsdp_axes=({!r},) to export it.)".format(
                            names[i]
                        )
                    )
                # non-leading named dims keep their global shape — nothing
                # to do
            return value
        return x

    def collapse(tree):
        if isinstance(tree, dict):
            if set(tree.keys()) == {"sharded"}:
                return collapse(tree["sharded"])
            return {k: collapse(v) for k, v in tree.items()}
        return tree

    unboxed = jax.tree_util.tree_map(
        unbox, params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )
    import flax

    if isinstance(unboxed, flax.core.FrozenDict):
        unboxed = unboxed.unfreeze()
    return collapse(unboxed)


class TPMLP(nn.Module):
    """Megatron MLP block: column-parallel up, activation, row-parallel down.

    One forward psum per block; the backward all-reduce pairs with the
    column layer's gradient.
    """

    hidden_features: int
    out_features: int
    axis_name: str = "model"
    activation: Callable = nn.gelu
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = TPDense(
            features=self.hidden_features,
            axis_name=self.axis_name,
            style="column",
            dtype=self.dtype,
            name="up",
        )(x)
        h = self.activation(h)
        y = TPDense(
            features=self.out_features,
            axis_name=self.axis_name,
            style="row",
            dtype=self.dtype,
            name="down",
        )(h)
        return y
