"""Training entrypoint: one script for every parallelism strategy.

Usage:
    python train.py --config=configs/mlp_dp_cpu.py            # reference parity
    python train.py --config=configs/gpt2_125m_dp.py          # pure DP
    python train.py --config=configs/gpt2_125m_tp.py          # 1-D tensor parallel
    python train.py --config=configs/gpt2_350m_pp.py          # 4-stage GPipe
    python train.py --config=configs/llama_1b_3d.py           # DP x TP x PP
    python train.py --config=configs/tiny_3d_cpu.py --config.steps=5

Any config field can be overridden on the CLI (``--config.steps=100``,
``--config.mesh.model=2`` ...) — the flag system the reference imported but
never wired up (SURVEY.md §5, config/flag row).
"""

from absl import app, flags, logging
from ml_collections import config_flags

_CONFIG = config_flags.DEFINE_config_file("config", None, "Training config file.")


def main(argv):
    del argv
    cd = _CONFIG.value
    from tpu_parallel.runtime import initialize, process_info, simulate_cpu_devices
    from tpu_parallel.train_lib import Trainer, TrainerConfig

    # Distributed bootstrap first: jax.distributed.initialize must run before
    # the first backend touch (simulate_cpu_devices initializes the backend to
    # validate its post-condition).
    initialize()
    sim = cd.get("simulate_cpu_devices", 0)
    if sim:
        simulate_cpu_devices(sim)
    logging.info("topology: %s", process_info())

    trainer_cd = dict(cd)
    trainer_cd.pop("simulate_cpu_devices", None)
    config = TrainerConfig.from_config_dict(trainer_cd)
    trainer = Trainer(config)
    logging.info(
        "model=%s params=%.1fM mesh=%s",
        config.model,
        trainer.num_params / 1e6,
        dict(trainer.mesh.shape),
    )

    def log_fn(step, metrics):
        parts = " ".join(f"{k}={v:.4f}" for k, v in sorted(metrics.items()))
        logging.info("step %d: %s", step, parts)

    final = trainer.train(log_fn=log_fn)
    logging.info("final: %s", final)


if __name__ == "__main__":
    app.run(main)
