from tpu_parallel.utils.logging_utils import MetricLogger, print_exception
from tpu_parallel.utils.profiling import (
    mfu,
    peak_flops,
    sync,
    timeit,
    trace,
    transformer_flops_per_token,
)

__all__ = [
    "MetricLogger",
    "print_exception",
    "mfu",
    "peak_flops",
    "sync",
    "timeit",
    "trace",
    "transformer_flops_per_token",
]
