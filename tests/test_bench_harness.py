"""bench.py's wedge-resilience contract, exercised for real in
subprocesses — plus the serve_bench workload-schedule helpers (trace
record/replay exchange format, priority/deadline distribution knobs).

The round-3 lesson: BENCH_r03.json was a bare watchdog zero.  The parent
must (a) never import jax itself, (b) report WHICH phase died, and (c)
carry the last good TPU measurement into the failure payload so a flaky
transport cannot erase the round's record.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _serve_bench():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import serve_bench
    finally:
        sys.path.pop(0)
    return serve_bench


def test_parse_dist():
    sb = _serve_bench()
    assert sb.parse_dist("0:6,1:3,2:1") == [
        (0.0, 6.0), (1.0, 3.0), (2.0, 1.0)
    ]
    assert sb.parse_dist("2.0:3,none:1") == [(2.0, 3.0), (None, 1.0)]
    assert sb.parse_dist("5") == [(5.0, 1.0)]  # weight defaults to 1
    for bad in ("", "x:y", "1:-2"):
        with pytest.raises(SystemExit):
            sb.parse_dist(bad)


def test_schedule_dists_deterministic_and_replayable(tmp_path):
    """--priority-dist / --deadline-dist satellite: the shaped schedule
    (a) leaves the arrival stream bit-identical to the unshaped one at
    the same seed (pre-existing records stay comparable), (b) is a pure
    function of (seed, dists), and (c) round-trips through the trace
    record/replay exchange format with every drawn field intact — a
    replayed overload trace exercises priority shedding as recorded."""
    sb = _serve_bench()
    prompts = [[1, 2, 3]] * 40
    groups = [0] * 40
    pdist = sb.parse_dist("0:6,1:3,2:1")
    ddist = sb.parse_dist("2.0:3,none:1")
    plain = sb.build_schedule(prompts, groups, 8.0, 5, 4)
    shaped = sb.build_schedule(
        prompts, groups, 8.0, 5, 4,
        priority_dist=pdist, deadline_dist=ddist,
    )
    assert [e["arrival"] for e in plain] == [e["arrival"] for e in shaped]
    assert all(
        e["priority"] == 0 and e["deadline"] is None for e in plain
    )
    assert {e["priority"] for e in shaped} == {0, 1, 2}
    assert any(e["deadline"] is None for e in shaped)
    assert any(e["deadline"] == 2.0 for e in shaped)
    again = sb.build_schedule(
        prompts, groups, 8.0, 5, 4,
        priority_dist=pdist, deadline_dist=ddist,
    )
    assert shaped == again
    path = str(tmp_path / "trace.jsonl")
    sb.write_trace(
        path, shaped,
        meta={"priority_dist": "0:6,1:3,2:1", "deadline_dist": "2.0:3,none:1"},
    )
    with open(path) as fh:
        header = json.loads(fh.readline())
    assert header["record"] == "trace_meta"
    assert header["priority_dist"] == "0:6,1:3,2:1"
    replayed = sb.load_trace(path)
    assert [
        (e["priority"], e["deadline"], e["prompt"]) for e in replayed
    ] == [
        (e["priority"], e["deadline"], e["prompt"]) for e in shaped
    ]


def _run_bench(extra_env):
    env = dict(os.environ)
    # force the CPU backend in the children; a tiny budget makes the probe
    # time out instantly, modeling the wedged relay
    env.update(
        PYTHONPATH="", JAX_PLATFORMS="cpu", BENCH_RETRY_PAUSE_SECS="1",
        **extra_env,
    )
    proc = subprocess.run(
        [sys.executable, BENCH], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        timeout=300,
    )
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    return proc.returncode, json.loads(line)


@pytest.mark.fast
def test_prompt_zipf_deterministic_and_replayable(tmp_path):
    """--prompt-zipf satellite: the Zipf multi-tenant mix (a) leaves the
    arrival stream bit-identical to unshaped schedules at the same seed
    (tenant/suffix draws run on child rngs), (b) is a pure function of
    (seed, S, tenants) with the head tenant genuinely hottest, and (c)
    round-trips through the trace exchange format with the tenant index
    riding ``prefix_group`` — a recorded Zipf workload replays exactly."""

    class _Cfg:
        vocab_size = 97
        seq_len = 64

    sb = _serve_bench()
    with pytest.raises(SystemExit):
        sb.parse_zipf("nope")
    with pytest.raises(SystemExit):
        sb.parse_zipf("0:4")
    assert sb.parse_zipf("1.2:16") == (1.2, 16)
    kw = dict(
        n_requests=60, prompt_min=1, prompt_max=6, prefix_len=8,
        seed=5, zipf_s=1.3, tenants=8,
    )
    p1, g1 = sb.make_zipf_prompts(_Cfg, **kw)
    p2, g2 = sb.make_zipf_prompts(_Cfg, **kw)
    assert p1 == p2 and g1 == g2  # pure function of (seed, shape)
    counts = [g1.count(t) for t in range(8)]
    assert counts[0] == max(counts) and counts[0] > sum(counts) / 8, (
        f"rank-1 tenant not hottest under Zipf: {counts}"
    )
    # same-tenant prompts share their header verbatim
    by_tenant = {}
    for p, g in zip(p1, g1):
        by_tenant.setdefault(g, p[:8])
        assert p[:8] == by_tenant[g]
    # arrivals come from build_schedule's OWN rng: bit-identical to the
    # unshaped workload at the same seed
    plain = sb.build_schedule([[1, 2, 3]] * 60, [0] * 60, 8.0, 5, 4)
    zipf = sb.build_schedule(p1, g1, 8.0, 5, 4)
    assert [e["arrival"] for e in plain] == [e["arrival"] for e in zipf]
    # trace round trip carries prompts AND tenant indices exactly
    path = str(tmp_path / "zipf.jsonl")
    sb.write_trace(path, zipf, meta=dict(prompt_zipf="1.3:8"))
    loaded = sb.load_trace(path)
    assert [e["prompt"] for e in loaded] == [e["prompt"] for e in zipf]
    assert [e["prefix_group"] for e in loaded] == g1


def _bench_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_under_test", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeTime:
    """Deterministic monotonic clock: each read advances a fixed step,
    each sleep advances by the requested amount — no wall time at all."""

    def __init__(self, step=0.5):
        self.t = 0.0
        self.step = step
        self.slept = []

    def __call__(self):
        self.t += self.step
        return self.t

    def sleep(self, seconds):
        self.slept.append(seconds)
        self.t += seconds


def test_wedge_reports_phase_and_carries_last_good(
    tmp_path, capsys, monkeypatch
):
    """The wedge contract, driven on the injectable seam instead of a
    wall-clock race: the old subprocess form set BENCH_WATCHDOG_SECS=3
    and ASSUMED the jax-import probe could never beat its 1s timeout —
    on a warm page cache it does, the probe passes, and phase 2 fails
    with "bench" instead of "probe".  A fake runner that always wedges
    removes the machine-speed dependence while exercising the real
    parent_main retry/report logic."""
    bench = _bench_module()
    fake = {
        "metric": "tokens/sec/chip", "value": 99999.0, "mfu": 0.42,
        "device": "TPU v5 lite", "ts": "2026-07-30T00:00:00Z",
        "commit": "abc1234",
    }
    # isolated last-good record: the real repo artifact must never be
    # touched by tests (a hard kill would leave a fabricated measurement)
    last_good = tmp_path / "BENCH_LAST_GOOD.json"
    last_good.write_text(json.dumps(fake))
    monkeypatch.setenv("BENCH_WATCHDOG_SECS", "1800")
    monkeypatch.setenv("BENCH_RETRY_PAUSE_SECS", "60")
    clk = _FakeTime()
    calls = []

    def wedged_run(cmd, timeout, env=None):
        calls.append((list(cmd), timeout))
        return None, "", True  # the probe hangs until its timeout

    with pytest.raises(SystemExit) as exc:
        bench.parent_main(
            run=wedged_run, monotonic=clk, sleep=clk.sleep,
            last_good_path=str(last_good),
        )
    assert exc.value.code == 3
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 0
    assert payload["phase"] == "probe"
    assert payload["last_good"]["value"] == 99999.0
    assert payload["last_good"]["commit"] == "abc1234"
    # exactly one retry after the documented pause, never the bench child
    assert len(calls) == 2
    assert clk.slept == [60.0]
    assert all("-c" in cmd for cmd, _ in calls)


def test_wedge_bench_phase_retries_once_then_reports(
    tmp_path, capsys, monkeypatch
):
    """Probe healthy, measurement wedged: the parent respawns exactly
    once (warm-cache retry), then fails with phase "bench" — the half of
    the watchdog contract the subprocess test could only reach by
    accident of machine speed."""
    bench = _bench_module()
    monkeypatch.setenv("BENCH_WATCHDOG_SECS", "1800")
    monkeypatch.setenv("BENCH_RETRY_PAUSE_SECS", "60")
    clk = _FakeTime()
    calls = []

    def run(cmd, timeout, env=None):
        calls.append((list(cmd), timeout, env))
        if "-c" in cmd:
            return 0, "BENCH-PROBE-OK cpu\n", False
        return None, "", True  # the measurement child wedges

    with pytest.raises(SystemExit) as exc:
        bench.parent_main(
            run=run, monotonic=clk, sleep=clk.sleep,
            last_good_path=str(tmp_path / "none.json"),
        )
    assert exc.value.code == 3
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["phase"] == "bench"
    assert "wedged" in payload["error"]
    assert "last_good" not in payload  # no record to carry, none invented
    bench_calls = [c for c in calls if "-c" not in c[0]]
    assert len(bench_calls) == 2
    assert all(c[2].get("BENCH_CHILD") == "1" for c in bench_calls)


def test_daemon_journal_replays_as_workload(tmp_path):
    """One journal format, not two: serve_bench --trace-replay (alias
    --workload) loads a daemon write-ahead journal directly — submit
    records become the schedule (arrivals rebased to the first submit,
    bookkeeping records skipped, torn tail tolerated), and the loaded
    schedule round-trips through the plain trace format unchanged."""
    from tpu_parallel.daemon import JournalWriter

    sb = _serve_bench()

    class Clk:
        def __init__(self):
            self.t = 100.0

        def __call__(self):
            self.t += 1.0
            return self.t

    path = str(tmp_path / "journal.jsonl")
    w = JournalWriter(path, Clk())
    prompts = [[4, 5, 6], [7, 8], [9, 10, 11, 12]]
    for i, p in enumerate(prompts):
        w.append({
            "record": "submit", "request_id": f"r{i}",
            "dedupe_token": f"tok-{i}", "client_id": "c",
            "arrival": 100.0 + 2.0 * i,
            "prompt": p, "prompt_len": len(p), "prefix_group": 0,
            "priority": i, "deadline": 3.5 if i == 2 else None,
            "max_new_tokens": 8,
        })
        w.append({
            "record": "tokens", "request_id": f"r{i}",
            "index": 0, "tokens": [1, 2],
        })
    w.append({
        "record": "terminal", "request_id": "r0",
        "status": "finished", "finish_reason": "length", "n_tokens": 8,
    })
    w.close()
    with open(path, "a") as fh:
        fh.write('{"record": "tokens", "request_id": "r1", "tok')  # torn

    sched = sb.load_trace(path)
    assert [e["prompt"] for e in sched] == prompts
    assert [e["arrival"] for e in sched] == [0.0, 2.0, 4.0]  # rebased
    assert [e["priority"] for e in sched] == [0, 1, 2]
    assert sched[2]["deadline"] == 3.5
    assert all(e["max_new_tokens"] == 8 for e in sched)
    # time compression behaves exactly like trace replay
    fast = sb.load_trace(path, time_compress=2.0)
    assert [e["arrival"] for e in fast] == [0.0, 1.0, 2.0]
    # round trip through the PLAIN trace format: identical schedule
    trace = str(tmp_path / "trace.jsonl")
    sb.write_trace(trace, sched, meta=dict(source="journal"))
    assert sb.load_trace(trace) == sched
    # the requests build exactly like trace entries
    req = sb._schedule_request(sched[2])
    assert list(req.prompt) == prompts[2]
    assert req.priority == 2 and req.deadline == 3.5


def test_journal_workload_multi_lifetime_rebase_and_corruption(tmp_path):
    """Journal arrival stamps are process-monotonic, NOT comparable
    across restarts: a journal spanning a crash (second life's clock
    restarts near zero) must replay in FILE (= seq) order with monotone
    rebased arrivals — not scrambled by a min-rebase sort.  And garbage
    anywhere but the tail refuses loudly instead of silently replaying
    a smaller workload."""
    import json

    sb = _serve_bench()
    path = str(tmp_path / "journal.jsonl")

    def sub(seq, rid, arrival):
        return {"record": "submit", "seq": seq, "request_id": rid,
                "arrival": arrival, "prompt": [1, 2], "prompt_len": 2,
                "prefix_group": 0, "priority": 0, "deadline": None,
                "max_new_tokens": 4}

    records = [
        {"record": "journal_meta", "journal_version": 1, "seq": 0},
        sub(1, "a", 100.0),
        sub(2, "b", 103.0),
        # kill -9; restart: new process, clock restarts LOW
        {"record": "recovery", "seq": 3, "replayed": 1},
        sub(4, "c", 0.5),
        sub(5, "d", 2.5),
    ]
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    sched = sb.load_trace(path)
    # file order preserved — life 2 does NOT jump ahead of life 1
    assert [e["prompt_len"] for e in sched] == [2, 2, 2, 2]
    assert [e["arrival"] for e in sched] == [0.0, 3.0, 3.0, 5.0]
    arr = [e["arrival"] for e in sched]
    assert arr == sorted(arr)  # monotone across the lifetime seam
    # mid-file garbage: typed refusal, not a silently smaller workload
    lines = open(path).read().splitlines()
    lines.insert(2, '{"record": "submit", "request_id": "x", "arri')
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    with pytest.raises(SystemExit):
        sb.load_trace(path)


def test_journal_workload_rejects_crc_failed_records(tmp_path):
    """Workload replay and recovery share ONE verification helper
    (``journal.record_crc_ok``): a CRC-failed record is rejected by
    ``load_trace`` exactly as ``read_journal`` rejects it — tolerated
    once at the tail, typed refusal anywhere else.  Before this,
    replay trusted any PARSEABLE record and a bit-rotted journal could
    silently replay a workload recovery would never accept."""
    from tpu_parallel.daemon import JournalWriter, read_journal
    from tpu_parallel.daemon.journal import encode_record

    sb = _serve_bench()
    path = str(tmp_path / "journal.jsonl")

    def sub(seq, rid, arrival):
        line, _ = encode_record({
            "record": "submit", "seq": seq, "request_id": rid,
            "arrival": arrival, "prompt": [1, 2], "prompt_len": 2,
            "prefix_group": 0, "priority": 0, "deadline": None,
            "max_new_tokens": 4, "at": 0.0,
        })
        return line

    meta, _ = encode_record(
        {"record": "journal_meta", "journal_version": 2, "seq": 0}
    )
    lines = [meta, sub(1, "a", 1.0), sub(2, "b", 2.0), sub(3, "c", 3.0)]
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    assert len(sb.load_trace(path)) == 3  # clean journal replays whole
    # one corrupted digit in the TAIL record (crc left stale): both
    # surfaces tolerate it as tail damage — the workload just shrinks
    tail_rot = lines[:3] + [
        lines[3].replace('"arrival": 3.0', '"arrival": 9.0')
    ]
    with open(path, "w") as fh:
        fh.write("\n".join(tail_rot) + "\n")
    assert read_journal(path)[1] == 1
    assert [e["arrival"] for e in sb.load_trace(path)] == [0.0, 1.0]
    # the same rot MID-file: both surfaces refuse loudly
    mid_rot = [
        lines[0],
        lines[1].replace('"arrival": 1.0', '"arrival": 9.0'),
        lines[2], lines[3],
    ]
    with open(path, "w") as fh:
        fh.write("\n".join(mid_rot) + "\n")
    with pytest.raises(Exception):
        read_journal(path)
    with pytest.raises(SystemExit):
        sb.load_trace(path)
    # and a REAL writer's journal (crc on every record) replays whole
    real = str(tmp_path / "real.jsonl")
    w = JournalWriter(real, lambda: 0.0)
    w.append({"record": "submit", "request_id": "r", "arrival": 0.0,
              "prompt": [3], "prompt_len": 1, "prefix_group": 0,
              "priority": 0, "deadline": None, "max_new_tokens": 2})
    w.close()
    assert len(sb.load_trace(real)) == 1
