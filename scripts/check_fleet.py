"""Runtime gate: a multi-process fleet serves, survives a kill, warms.

Like ``check_daemon`` this checker RUNS the product: it delegates to
``scripts/fleet_bench.py``'s ``run_smoke()`` — one fleet router over two
real daemon subprocesses on loopback ports, client traffic through the
router's daemon-identical HTTP/SSE contract, one seeded SIGKILL of a
daemon mid-stream (the victim's streams must continue bitwise on the
survivor via forced-prefix handoff), and at least one remote KV
migration landing with a typed ``imported`` verdict — so ``python
scripts/check_all.py`` catches a fleet that cannot complete its own
failure story, not just one whose modules parse clean.

Registered in ``check_all.RUNTIME_CHECKS`` (not ``CHECKERS``): the AST
gates stay instant for ``tests/test_checkers.py::test_all_ast_gates``,
while this one runs as its own tier-1 entry
(``tests/test_fleet.py::test_fleet_smoke_subprocess``) and in the
``check_all`` CLI.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List, Sequence

SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))

DEFAULT_PATHS: Sequence[str] = ()  # runtime check: no tree to walk


def check_paths(paths: Sequence[str] = DEFAULT_PATHS) -> List[str]:
    spec = importlib.util.spec_from_file_location(
        "fleet_bench", os.path.join(SCRIPTS_DIR, "fleet_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return [f"fleet smoke: {p}" for p in mod.run_smoke()]


def main(argv: List[str]) -> int:
    problems = check_paths()
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(f"check_fleet: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("check_fleet: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
