"""Fleet soak: one wire-level router over N daemon PROCESSES, under
seeded host kills — the cross-host acceptance gate (docs/14_fleet.md).

``daemon_bench`` proves one process survives its own death through the
journal.  This bench proves the FLEET survives a host's death through
the router: clients talk only to the router (the daemon's exact
HTTP/SSE contract re-served by ``tpu_parallel/fleet/http.py``), daemons
are killed -9 at seeded points mid-traffic, and the invariants are
judged fleet-wide:

1. **zero lost accepted requests** — every submission the router
   acknowledged reaches exactly one ``finished`` terminal, even when
   its backing daemon was SIGKILLed mid-stream (cross-host handoff:
   prompt + delivered tokens replayed onto a survivor as a forced
   prefix);
2. **zero duplicate completions** — the router's dedupe ledger answers
   client retries with the original request id across host deaths
   (the dead host's journal is unreachable; the ledger is the
   fleet-wide authority);
3. **bitwise token parity** — every completed stream, including every
   handed-off one, equals the static greedy reference: the host death
   changed NOTHING about the output;
4. **remote KV migration lands** — a killed daemon restarted on its
   port is warm-started by the router from a healthy donor over the
   ``kv_wire`` codec, with at least one typed ``imported`` verdict;
   and the corrupt-injection leg (one seeded bit flipped in an
   exported wire blob) is refused TYPED by the importer — corrupt
   bytes never land, recompute covers the miss;
5. **graceful exits** — SIGTERM drains the router and every daemon to
   exit 0.

Entry modes:

- ``--smoke``: the fast CI gate (``scripts/check_fleet.py`` and tier-1
  via ``tests/test_fleet.py``): router + 2 daemons on loopback ports,
  one SIGKILL mid-stream, one recovery warm start, one corrupt-import
  refusal, and a disagg leg (a second, role-pinned router over the
  same daemons: bitwise prefill->decode handoff, then a dead decode
  peer resolving as a typed fallback).  Bounded wall time; one model
  build in the parent (the greedy reference) plus one per child.
- ``--soak SEED``: the acceptance soak — per seeded trial: router + 3
  daemons, a seeded request schedule, a seeded victim SIGKILLed at a
  seeded point, full invariant sweep, restart + warm start, corrupt
  leg, graceful stop.  ``--record FLEET_r01.json`` writes the
  per-trial evidence.
- ``--disagg SEED``: the disaggregation bench — 1-prefill/2-decode vs
  3-mixed at equal hardware on one seeded schedule (a long-prefill
  burst contending with decode-heavy probes); records decode ITL p95,
  TTFT, and handoff bytes/latency per leg into ``FLEET_r02.json``,
  failing on any lost/duplicated/non-bitwise stream.
- ``--serve``: INTERNAL daemon child — the ``daemon_bench`` child with
  radix-cached engines (``kv_block_tokens=4`` + ``kv_radix_cache``) so
  peer KV export/import has chains to ship.
- ``--route``: INTERNAL router child — a :class:`FleetRouter` on the
  WallClock + urllib transport, its probe pump on the main thread,
  SIGTERM -> stop -> exit 0.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

DEFAULT_NEW_TOKENS = 8
# long enough that a seeded kill lands mid-stream, while prompt +
# budget stays inside the tiny_test model's seq_len of 32
HANDOFF_NEW_TOKENS = 20
READY_TIMEOUT = 300.0  # cold jax import + compile on a 1-core box
BLOCK_TOKENS = 4  # the children's paged-KV block size
TERMINAL = ("finished", "failed", "cancelled", "rejected", "expired")


# -- small plumbing ----------------------------------------------------------


def pick_ports(n):
    """Reserve n distinct loopback ports (portpicker when available,
    else bind-to-0 probing — daemons need FIXED ports so a restarted
    victim comes back at the address the router knows it by)."""
    try:
        import portpicker

        return [portpicker.pick_unused_port() for _ in range(n)]
    except ImportError:
        socks, ports = [], []
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
        for s in socks:
            s.close()
        return ports


def http_json(method, url, body=None, timeout=120.0):
    """One JSON request; returns (status_code, payload) and never
    raises on HTTP error codes (connection errors DO raise)."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


def http_bytes(method, url, data=None, timeout=120.0):
    """Binary-bodied sibling: returns (status_code, raw_bytes)."""
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/octet-stream")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


def wait_ready(ready_file, proc, timeout=READY_TIMEOUT):
    """Poll for a child's ready file; returns its payload dict."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"child exited rc={proc.returncode} before ready"
            )
        if os.path.exists(ready_file):
            try:
                with open(ready_file) as fh:
                    info = json.load(fh)
                if "port" in info:
                    return info
            except (ValueError, OSError):
                pass  # mid-write
        time.sleep(0.05)
    raise RuntimeError(f"child not ready within {timeout}s")


class Peer:
    """One daemon child the parent manages: fixed port, its journal,
    its ready file, and the live Popen handle (replaced on restart)."""

    def __init__(self, tmpdir, name, port, role="mixed", tick_sleep=0.0,
                 trace_log=None):
        self.name = name
        self.port = port
        self.role = role
        self.tick_sleep = tick_sleep
        self.addr = f"127.0.0.1:{port}"
        self.journal = os.path.join(tmpdir, f"{name}.jsonl")
        self.ready = os.path.join(tmpdir, f"{name}.ready.json")
        self.trace_log = trace_log
        self.proc = None
        self.pid = None

    def spawn(self, grace=60.0):
        if os.path.exists(self.ready):
            os.remove(self.ready)
        cmd = [
            sys.executable, os.path.abspath(__file__), "--serve",
            "--journal", self.journal, "--ready-file", self.ready,
            "--port", str(self.port), "--grace", str(grace),
            "--role", self.role, "--tick-sleep", str(self.tick_sleep),
        ]
        if self.trace_log:
            cmd += ["--trace-log", self.trace_log]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(cmd, env=env)
        return self

    def wait_ready(self):
        info = wait_ready(self.ready, self.proc)
        self.pid = info["pid"]
        return info

    def sigkill(self):
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)


def spawn_router(tmpdir, peer_addrs, warm_blocks=64, roles=None,
                 name="router", trace_log=None):
    ready = os.path.join(tmpdir, f"{name}.ready.json")
    if os.path.exists(ready):
        os.remove(ready)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--route",
        "--peers", ",".join(peer_addrs), "--ready-file", ready,
        "--warm-blocks", str(warm_blocks),
    ]
    if roles:
        cmd += ["--roles", ",".join(roles)]
    if trace_log:
        cmd += ["--trace-log", trace_log]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(cmd, env=env), ready


def stop_gracefully(proc, problems, label, grace=120.0):
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        problems.append(f"{label}: SIGTERM did not exit within grace")
        return
    if rc != 0:
        problems.append(f"{label}: drain exit code {rc} != 0")


# -- schedule + references ---------------------------------------------------


def make_schedule(seed, n_requests, new_tokens, prefix=()):
    """Seeded prompts + dedupe tokens.  ``prefix`` makes a group of
    prompts share a block-aligned head — the hot chains the radix
    caches build and the KV migration legs ship."""
    rnd = random.Random(seed)
    schedule = []
    for i in range(n_requests):
        tail = rnd.randrange(3, 10)
        prompt = list(prefix) + [
            rnd.randrange(1, 250) for _ in range(tail)
        ]
        schedule.append({
            "dedupe_token": f"fleet-{seed}-{i}",
            "prompt": prompt,
            "max_new_tokens": new_tokens,
        })
    return schedule


def shared_prefix(seed, blocks=2):
    rnd = random.Random(seed ^ 0x9E1F)
    return [
        rnd.randrange(1, 250) for _ in range(blocks * BLOCK_TOKENS)
    ]


def greedy_references(schedule):
    """Static-generate the greedy continuation for every prompt — the
    parity oracle every fleet stream must match bitwise, through any
    number of host deaths."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_parallel.models import GPTLM, tiny_test
    from tpu_parallel.models.generate import generate

    cfg = tiny_test(remat=False)
    model = GPTLM(cfg)
    probe = jnp.zeros((1, 16), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    refs = {}
    for entry in schedule:
        cont = np.asarray(generate(
            model, params,
            jnp.asarray(entry["prompt"], jnp.int32)[None, :],
            max_new_tokens=entry["max_new_tokens"],
        ))[0]
        refs[entry["dedupe_token"]] = [int(t) for t in cont]
    return refs


# -- the children ------------------------------------------------------------


def serve(args):
    """Daemon child: daemon_bench's serve with radix-cached engines so
    ``/v1/kv/export`` has hot chains to ship."""
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(REPO_ROOT, ".pytest_xla_cache"),
    )
    from tpu_parallel.cluster import Frontend, FrontendConfig
    from tpu_parallel.daemon import (
        DaemonConfig,
        DaemonHTTPServer,
        ServingDaemon,
    )
    from tpu_parallel.models import GPTLM, tiny_test
    from tpu_parallel.obs.registry import MetricRegistry
    from tpu_parallel.obs.spool import SpanSpool
    from tpu_parallel.obs.tracer import Tracer
    from tpu_parallel.serving import SchedulerConfig, ServingEngine

    from tpu_parallel.daemon.wallclock import WallClock

    # --trace-log arms distributed tracing: ONE tracer shared by the
    # engines, the frontend and the daemon (so every layer's spans land
    # in one list), spooled to the named JSONL by the daemon's tick.
    # The tracer runs on the daemon's OWN clock — span timestamps and
    # the ``ts`` this process reports on the wire must share a base or
    # the stitcher's clock-offset math rebases against the wrong zero.
    wallclock = WallClock()
    tracer = Tracer(wallclock) if args.trace_log else None
    spool = (
        SpanSpool(args.trace_log, proc=f"daemon:{args.role}")
        if args.trace_log else None
    )

    cfg = tiny_test(remat=False)
    model = GPTLM(cfg)
    probe = jax.numpy.zeros((1, 16), jax.numpy.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]

    def frontend_factory(clock):
        engines = [
            ServingEngine(
                model, params, n_slots=args.slots,
                scheduler=SchedulerConfig(max_prefills_per_tick=2),
                kv_block_tokens=BLOCK_TOKENS, prefix_cache_size=64,
                kv_radix_cache=True, tracer=tracer,
                # one decode token per paced tick: the fused-scan
                # default drains a whole budget in ~3 ticks, which no
                # tick pacing can stretch — and mid-flight legs (kills,
                # disagg migrations) need requests that LIVE a while
                decode_steps_per_tick=1 if args.tick_sleep > 0 else "auto",
            )
            for _ in range(args.replicas)
        ]
        fe = Frontend(
            engines, router="least",
            config=FrontendConfig(restart=None),
            clock=clock, registry=MetricRegistry(), tracer=tracer,
        )
        if args.tick_sleep > 0:
            # pace each pump tick like a realistically-sized model's
            # decode step: the tiny CPU model otherwise drains a whole
            # token budget faster than one KV-handoff round-trip, which
            # makes mid-flight legs (kills, disagg migrations) a race
            orig_step = fe.step

            def paced_step(*a, **kw):
                out = orig_step(*a, **kw)
                time.sleep(args.tick_sleep)
                return out

            fe.step = paced_step
        return fe

    daemon = ServingDaemon(
        frontend_factory, args.journal,
        config=DaemonConfig(
            grace_seconds=args.grace, fsync_batch=args.fsync_batch,
            role=args.role,
        ),
        clock=wallclock,
        span_spool=spool,
    )
    server = DaemonHTTPServer(daemon, port=args.port).start()
    daemon.install_signals()
    with open(args.ready_file + ".tmp", "w") as fh:
        json.dump({"port": server.port, "pid": os.getpid()}, fh)
    os.replace(args.ready_file + ".tmp", args.ready_file)
    rc = daemon.run()
    server.stop()
    return rc


def route(args):
    """Router child: FleetRouter + FleetHTTPServer; the probe pump owns
    the main thread; SIGTERM stops it for a clean exit 0."""
    from tpu_parallel.daemon.wallclock import WallClock
    from tpu_parallel.fleet import (
        FleetHTTPServer,
        FleetRouter,
        HTTPFleetTransport,
        PeerPolicy,
    )
    from tpu_parallel.obs.registry import MetricRegistry
    from tpu_parallel.obs.spool import SpanSpool
    from tpu_parallel.obs.tracer import Tracer

    wallclock = WallClock()
    # same-clock rule as serve(): the router's clock_sync attrs
    # (t_send/t_recv on self.clock) and its span timestamps must share
    # a base for the stitcher's rebasing to be exact
    tracer = Tracer(wallclock) if args.trace_log else None
    spool = (
        SpanSpool(args.trace_log, proc="router")
        if args.trace_log else None
    )
    peers = [p for p in args.peers.split(",") if p]
    roles = None
    if args.roles:
        parts = [r for r in args.roles.split(",") if r]
        if len(parts) != len(peers):
            raise SystemExit("--roles must align 1:1 with --peers")
        roles = dict(zip(peers, parts))
    router = FleetRouter(
        peers,
        clock=wallclock,
        transport=HTTPFleetTransport(),
        roles=roles,
        # key placement on the shared-prefix head (2 KV blocks = 8
        # tokens): every request of a shared_prefix() group lands on
        # the same daemon, which is what makes its radix chains hot
        # and the kill leg's filler backlog actually pin one host
        buckets=(2 * BLOCK_TOKENS, 4 * BLOCK_TOKENS),
        # bench-paced breaker: detect a dead host in ~1s of probes and
        # readmit a rebooted one within 2s of it answering
        policy=PeerPolicy(
            probe_interval_seconds=0.5,
            degraded_after=1,
            dead_after=2,
            reprobe_backoff_seconds=0.5,
            reprobe_backoff_factor=2.0,
            reprobe_backoff_max=2.0,
            connect_timeout_seconds=5.0,
            request_timeout_seconds=120.0,
            stream_idle_timeout_seconds=15.0,
        ),
        registry=MetricRegistry(),
        warm_start_blocks=args.warm_blocks,
        tracer=tracer,
        span_spool=spool,
    )
    server = FleetHTTPServer(router, port=args.port).start()
    signal.signal(signal.SIGTERM, lambda *_: router.stop())
    with open(args.ready_file + ".tmp", "w") as fh:
        json.dump({"port": server.port, "pid": os.getpid()}, fh)
    os.replace(args.ready_file + ".tmp", args.ready_file)
    router.run(poll_seconds=0.1)
    server.stop()
    return 0


# -- fleet-side helpers ------------------------------------------------------


class StreamReader(threading.Thread):
    """Consume one router SSE stream to its terminal event."""

    def __init__(self, base, rid):
        super().__init__(daemon=True)
        self.url = f"{base}/v1/stream/{rid}"
        self.rid = rid
        self.events = []
        self.times = []  # wall-clock arrival per event (TTFT / ITL)
        self.t0 = None
        self.error = None

    def run(self):
        self.t0 = time.monotonic()
        try:
            req = urllib.request.Request(self.url)
            # generous per-read timeout: the router does not forward
            # keepalives, and a handoff can sit out a probe interval
            with urllib.request.urlopen(req, timeout=600) as resp:
                for raw in resp:
                    line = raw.strip()
                    if not line.startswith(b"data:"):
                        continue
                    ev = json.loads(line[len(b"data:"):].strip())
                    self.times.append(time.monotonic())
                    self.events.append(ev)
                    if ev.get("finished"):
                        return
        except Exception as exc:  # judged by the parent, not raised
            self.error = repr(exc)

    def tokens(self):
        return [e["token"] for e in self.events if "token" in e]

    def indices(self):
        return [e["index"] for e in self.events if "token" in e]

    def ttft(self):
        """Stream-open to first relayed token, or None."""
        for ev, at in zip(self.events, self.times):
            if "token" in ev:
                return at - self.t0
        return None

    def itls(self):
        """Inter-token gaps over the relayed stream (decode latency as
        the client experiences it, handoff stalls included)."""
        arrivals = [
            at for ev, at in zip(self.events, self.times) if "token" in ev
        ]
        return [b - a for a, b in zip(arrivals, arrivals[1:])]


def wait_finished(base, rids, refs, problems, timeout=240.0, label=""):
    """Poll router results until every rid is terminal; judge lost
    work and bitwise parity.  Returns token -> final record."""
    deadline = time.monotonic() + timeout
    pending = dict(rids)
    finished = {}
    while pending and time.monotonic() < deadline:
        for tok, rid in list(pending.items()):
            code, rec = http_json("GET", f"{base}/v1/result/{rid}")
            if code == 200 and rec.get("status") in TERMINAL:
                finished[tok] = rec
                del pending[tok]
        time.sleep(0.05)
    for tok, rid in pending.items():
        problems.append(f"{label}{tok} ({rid}): never terminal")
    for tok, rec in finished.items():
        if rec["status"] != "finished":
            problems.append(
                f"{label}{tok}: status {rec['status']} "
                f"({rec['finish_reason']}) — lost accepted work"
            )
        elif refs is not None and rec["tokens"] != refs[tok]:
            problems.append(
                f"{label}{tok}: tokens diverge from the greedy "
                "reference through the fleet (SILENT WRONG TOKENS)"
            )
    return finished


def kill_when_mid_flight(reader, victim, problems, timeout=120.0):
    """Spin on the target's OWN relayed SSE events until its first
    token arrives, then SIGKILL the backing daemon — the only trigger
    fast enough when the tiny model decodes a whole slot's budget in
    milliseconds (a fixed sleep overshoots the stream; an HTTP result
    poll's router roundtrip can be slower than the stream itself).
    Returns True when the kill landed mid-flight."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        events = reader.events
        if events:
            if events[-1].get("finished"):
                break  # drained before the kill could land
            victim.sigkill()
            return True
        if not reader.is_alive():
            break
        time.sleep(0.0005)
    victim.sigkill()
    problems.append(
        "kill leg: target never observed mid-flight before the kill "
        f"(events={len(reader.events)}, alive={reader.is_alive()})"
    )
    return False


def read_metric(base, line_prefix):
    """Read one series value from the router's /metricsz text."""
    with urllib.request.urlopen(f"{base}/metricsz", timeout=30) as resp:
        text = resp.read().decode()
    for line in text.splitlines():
        if line.startswith(line_prefix + " "):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def read_metric_sum(base, name):
    """Sum every series of a labelled metric family (e.g. all
    ``reason=`` legs of ``fleet_handoff_fallbacks_total``)."""
    with urllib.request.urlopen(f"{base}/metricsz", timeout=30) as resp:
        text = resp.read().decode()
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name + "{") or line.startswith(name + " "):
            total += float(line.rsplit(" ", 1)[1])
    return total


def p95(samples):
    if not samples:
        return None
    ordered = sorted(samples)
    rank = max(1, -(-95 * len(ordered) // 100))  # ceil, 1-based
    return ordered[rank - 1]


def wait_metric(base, line_prefix, minimum, timeout=90.0):
    deadline = time.monotonic() + timeout
    value = 0.0
    while time.monotonic() < deadline:
        value = read_metric(base, line_prefix)
        if value >= minimum:
            return value
        time.sleep(0.25)
    return value


def corrupt_import_leg(donor_addr, target_addr, seed, problems):
    """Export real KV from ``donor``, flip ONE seeded bit, import into
    ``target``: the importer must refuse TYPED (a ``kv_wire`` reason),
    never land garbage.  Returns the typed reason (or None)."""
    from tpu_parallel.serving.kv_wire import WIRE_REASONS

    code, blob = http_bytes(
        "GET", f"http://{donor_addr}/v1/kv/export?max_blocks=16"
    )
    if code != 200:
        problems.append(f"corrupt leg: donor export -> {code}")
        return None
    if not blob:
        problems.append(
            "corrupt leg: donor exported no hot KV — nothing proved"
        )
        return None
    rnd = random.Random(seed ^ 0xB17)
    bit = rnd.randrange(len(blob) * 8)
    flipped = bytearray(blob)
    flipped[bit // 8] ^= 1 << (bit % 8)
    code, body = http_bytes(
        "POST", f"http://{target_addr}/v1/kv/import", bytes(flipped)
    )
    try:
        payload = json.loads(body or b"{}")
    except ValueError:
        payload = {}
    reason = payload.get("reason")
    if code != 400 or reason not in WIRE_REASONS:
        problems.append(
            f"corrupt leg: flipped-bit import answered {code} "
            f"{payload} — want a typed 400 refusal"
        )
        return None
    # the INTACT blob lands (or typed-falls-back) — the refusal above
    # was about the damage, not the transfer
    code, body = http_bytes(
        "POST", f"http://{target_addr}/v1/kv/import", blob
    )
    if code != 200:
        problems.append(f"corrupt leg: intact import -> {code} {body!r}")
    return reason


def direct_import_leg(donor_addrs, victim_addr, problems):
    """Deterministic warm-start freight: export hot chains from a
    daemon that served traffic while the victim was dead — chains the
    victim's own journal replay cannot have recovered — and land them
    directly.  Returns the count of typed ``imported`` verdicts."""
    for addr in sorted(donor_addrs):
        code, blob = http_bytes(
            "GET", f"http://{addr}/v1/kv/export?max_blocks=64"
        )
        if code != 200 or not blob:
            continue
        code, body = http_bytes(
            "POST", f"http://{victim_addr}/v1/kv/import", blob
        )
        if code != 200:
            problems.append(
                f"direct import into the recovered victim -> {code}"
            )
            continue
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            payload = {}
        if payload.get("imported", 0) >= 1:
            return payload["imported"]
    problems.append(
        "no remote KV import landed a typed `imported` verdict, even "
        "shipping downtime chains the victim provably never saw"
    )
    return 0


# -- the trace leg's stitch + verdict ----------------------------------------


def stitch_and_judge(trace_out, router_log, peers, rids, evidence):
    """Run ``scripts/trace_stitch.py`` over the router's and every
    peer's span log, then judge the stitched forest: each request in
    ``rids`` must map (via the router's ``route`` span) to a trace that
    is single-rooted, touches >= 2 pids and carries a cross-process
    parent link.  Fills ``evidence`` (the TRACE_r01 record) and returns
    a problem list."""
    from tpu_parallel.obs.spool import read_span_log

    problems = []
    cmd = [
        sys.executable,
        os.path.join(REPO_ROOT, "scripts", "trace_stitch.py"),
        trace_out, router_log,
    ] + [
        f"{p.trace_log}={p.addr}" for p in peers if p.trace_log
    ] + ["--summary"]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        problems.append(
            f"trace leg: stitch failed rc={res.returncode}: "
            f"{res.stderr.strip()}"
        )
        return problems
    try:
        summary = json.loads(res.stdout)
    except ValueError:
        problems.append(
            f"trace leg: stitch summary unparseable: {res.stdout!r}"
        )
        return problems
    with open(trace_out) as fh:
        stitched = json.load(fh)

    # rid -> trace id, from the router's root spans
    records, _skipped = read_span_log(router_log)
    trace_of_rid = {}
    span_counts = {"router": len(records)}
    for rec in records:
        if rec.get("kind") == "span" and rec.get("name") == "route":
            rid = (rec.get("attrs") or {}).get("rid")
            if rid and rec.get("trace_id"):
                trace_of_rid[rid] = rec["trace_id"]
    for p in peers:
        if p.trace_log:
            peer_records, _ = read_span_log(p.trace_log)
            span_counts[p.name] = len(peer_records)

    connected = 0
    for tok, rid in sorted(rids.items()):
        trace_id = trace_of_rid.get(rid)
        verdict = summary.get(trace_id) if trace_id else None
        if verdict is None:
            problems.append(
                f"trace leg: {tok} ({rid}) has no stitched trace"
            )
            continue
        broken = []
        if not verdict.get("single_rooted"):
            broken.append(f"roots={verdict.get('roots')}")
        if len(verdict.get("pids", [])) < 2:
            broken.append(f"pids={verdict.get('pids')}")
        if verdict.get("cross_process_links", 0) < 1:
            broken.append("no cross-process link")
        if broken:
            problems.append(
                f"trace leg: {tok} trace not connected: "
                + ", ".join(broken)
            )
        else:
            connected += 1
    flow_arrows = stitched.get("metadata", {}).get("flow_arrows", 0)
    if flow_arrows < 1:
        problems.append(
            "trace leg: stitched file carries no flow arrows"
        )
    evidence.update({
        "trace_file": trace_out,
        "requests": len(rids),
        "connected_traces": connected,
        "completeness": (
            round(connected / len(rids), 4) if rids else None
        ),
        "span_counts": span_counts,
        "stitched_traces": len(summary),
        "flow_arrows": flow_arrows,
        "trace_events": len(stitched.get("traceEvents", [])),
    })
    return problems


# -- modes -------------------------------------------------------------------


def run_smoke(tmpdir=None, keep=False, trace_out="", record=""):
    """router + 2 daemons -> traffic -> one SIGKILL mid-stream (bitwise
    handoff) -> victim restart (remote KV warm start) -> corrupt-import
    refusal -> graceful stop.  The gate check_fleet and tier-1 run.
    Returns a problem list.

    The TRACE leg rides the disagg leg: the daemons spool spans from
    boot, the disagg router runs traced, and after it drains the three
    span logs are stitched (``scripts/trace_stitch.py``) into ONE
    Perfetto file — every disagg request must form a single-rooted
    trace crossing >= 2 pids with a cross-process parent link.
    ``trace_out`` names the stitched file (default: inside tmpdir);
    ``record`` writes the TRACE_r01-shape evidence JSON."""
    import tempfile

    problems = []
    tmpdir = tmpdir or tempfile.mkdtemp(prefix="fleet_smoke_")
    trace_out = trace_out or os.path.join(tmpdir, "stitched_trace.json")
    ports = pick_ports(2)
    # paced ticks: the tiny model must not outrun the mid-flight legs
    # (the kill and the disagg migration both race one HTTP round-trip)
    peers = [
        Peer(tmpdir, f"d{i}", p, tick_sleep=0.01,
             trace_log=os.path.join(tmpdir, f"d{i}.trace.jsonl"))
        for i, p in enumerate(ports)
    ]
    trace_evidence = {}
    by_addr = {p.addr: p for p in peers}
    router_proc = None
    try:
        for p in peers:
            p.spawn()
        for p in peers:
            p.wait_ready()
        router_proc, rready = spawn_router(
            tmpdir, [p.addr for p in peers]
        )
        rport = wait_ready(rready, router_proc)["port"]
        base = f"http://127.0.0.1:{rport}"

        code, payload = http_json("GET", f"{base}/healthz")
        if code != 200 or not payload.get("ok"):
            problems.append(f"router healthz {code}: {payload}")

        # ---- warm traffic: shared-prefix group A, plus the kill-leg
        # entries (fillers pin the victim's slots so the target request
        # is guaranteed still mid-flight when the host dies)
        prefix_a = shared_prefix(31)
        sched = make_schedule(
            41, 2, DEFAULT_NEW_TOKENS, prefix=prefix_a
        )
        # the kill leg runs on a FRESH prefix: warm-cached chains would
        # make every prefill a radix hit and the whole backlog drains
        # in milliseconds — too fast to ever catch the target mid-flight
        rnd = random.Random(43)
        prefix_k = shared_prefix(33)
        fillers = [
            {
                "dedupe_token": f"fleet-fill-{i}",
                "prompt": prefix_k + [
                    rnd.randrange(1, 250) for _ in range(3)
                ],
                "max_new_tokens": HANDOFF_NEW_TOKENS,
            }
            for i in range(6)
        ]
        long_entry = {
            "dedupe_token": "fleet-long-0",
            "prompt": prefix_k + [7, 11, 13],
            "max_new_tokens": HANDOFF_NEW_TOKENS,
        }
        refs = greedy_references(sched + fillers + [long_entry])
        rids = {}
        for entry in sched:
            code, rec = http_json(
                "POST", f"{base}/v1/submit", entry
            )
            if code != 200:
                problems.append(f"submit {code}: {rec}")
                continue
            rids[entry["dedupe_token"]] = rec["request_id"]
        # fleet-wide idempotence: a retry answers the original record
        if rids:
            code, rec = http_json("POST", f"{base}/v1/submit", sched[0])
            first = rids[sched[0]["dedupe_token"]]
            if code != 200 or rec["request_id"] != first:
                problems.append(
                    f"fleet dedupe resubmit mismatched: {code} {rec}"
                )
        wait_finished(base, rids, refs, problems, label="warm: ")

        # ---- the kill leg: pin the victim's slots with filler work,
        # then SIGKILL the daemon backing the live target stream
        fill_rids = {}
        for entry in fillers:
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code != 200:
                problems.append(f"filler submit {code}: {rec}")
                continue
            fill_rids[entry["dedupe_token"]] = rec["request_id"]
        code, rec = http_json("POST", f"{base}/v1/submit", long_entry)
        if code != 200:
            problems.append(f"long submit {code}: {rec}")
            return problems
        rid_long = rec["request_id"]
        victim = by_addr[rec["peer"]]
        reader = StreamReader(base, rid_long)
        reader.start()
        if not kill_when_mid_flight(reader, victim, problems):
            return problems
        reader.join(timeout=420)
        if reader.is_alive():
            problems.append("kill leg: relay stream never terminated")
        elif reader.error:
            problems.append(f"kill leg: relay stream tore: {reader.error}")
        else:
            idxs = reader.indices()
            if idxs != list(range(len(idxs))):
                problems.append(
                    f"kill leg: client indices not contiguous: {idxs}"
                )
            if reader.tokens() != refs["fleet-long-0"]:
                problems.append(
                    "kill leg: handed-off stream diverges from the "
                    "greedy reference (NOT BITWISE)"
                )
            tail = reader.events[-1] if reader.events else {}
            if not tail.get("finished") or tail.get("status") != "finished":
                problems.append(f"kill leg: bad terminal event {tail}")
        code, rec = http_json("GET", f"{base}/v1/result/{rid_long}")
        if code != 200 or rec.get("handoffs", 0) < 1:
            problems.append(
                f"kill leg: no handoff recorded on the request: {rec}"
            )
        # the fillers shared the victim's slots: they hand off too, and
        # must finish bitwise on the survivor like any accepted work
        wait_finished(base, fill_rids, refs, problems, label="filler: ")
        survivor = next(p for p in peers if p is not victim)

        # ---- hot chains the victim never saw, then the corrupt leg
        prefix_b = shared_prefix(32)
        sched_b = make_schedule(
            42, 2, DEFAULT_NEW_TOKENS, prefix=prefix_b
        )
        refs_b = greedy_references(sched_b)
        rids_b = {}
        for entry in sched_b:
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code == 200:
                rids_b[entry["dedupe_token"]] = rec["request_id"]
            else:
                problems.append(f"post-kill submit {code}: {rec}")
        wait_finished(base, rids_b, refs_b, problems, label="post-kill: ")
        corrupt_import_leg(survivor.addr, survivor.addr, 5, problems)

        # ---- restart the victim: the router must warm-start it from
        # the survivor over the wire (>= 1 typed `imported` verdict)
        victim.spawn()
        victim.wait_ready()
        imported = wait_metric(
            base, 'fleet_kv_imports_total{status="imported"}', 1
        )
        if imported < 1:
            problems.append(
                "no remote KV import landed after the victim recovered "
                f"(imported={imported})"
            )
        # the recovered peer serves through the router again
        code, payload = http_json("GET", f"{base}/healthz")
        if code != 200:
            problems.append(f"post-recovery healthz {code}: {payload}")

        # ---- disagg leg: a SECOND router over the same two daemons,
        # roles pinned prefill/decode router-side.  Fresh prompts place
        # on the prefill peer, migrate to the decode peer at first
        # token, and the client stream must stay bitwise through the
        # move; a dead decode peer must resolve as a TYPED fallback to
        # colocated decode, never lost tokens.  (Router children are
        # cheap — no model build — so this reuses the warm daemons.)
        # DISTINCT cold prompts (a shared warm prefix makes every
        # prefill a radix hit and the whole batch drains before the
        # export round-trip can land), enough of them that the later
        # requests queue behind the prefill peer's slots: a queued
        # request's relay is attached before admission, so its first
        # token fires the export with most of the budget still pending
        rnd_d = random.Random(44)
        d_entries = [
            {
                "dedupe_token": f"fleet-dg-{i}",
                "prompt": [
                    rnd_d.randrange(1, 250) for _ in range(11)
                ],
                "max_new_tokens": HANDOFF_NEW_TOKENS,
            }
            for i in range(8)
        ]
        kill_entry = {
            "dedupe_token": "fleet-dg-kill",
            "prompt": [rnd_d.randrange(1, 250) for _ in range(11)],
            "max_new_tokens": HANDOFF_NEW_TOKENS,
        }
        refs_d = greedy_references(d_entries + [kill_entry])
        router2_log = os.path.join(tmpdir, "router2.trace.jsonl")
        router2, r2ready = spawn_router(
            tmpdir, [p.addr for p in peers],
            roles=["prefill", "decode"], name="router2",
            trace_log=router2_log,
        )
        try:
            r2port = wait_ready(r2ready, router2)["port"]
            base2 = f"http://127.0.0.1:{r2port}"
            rids_d, readers_d = {}, {}
            for entry in d_entries:
                code, rec = http_json("POST", f"{base2}/v1/submit", entry)
                if code != 200:
                    problems.append(f"disagg submit {code}: {rec}")
                    continue
                tok = entry["dedupe_token"]
                rids_d[tok] = rec["request_id"]
                if rec.get("peer") != peers[0].addr:
                    problems.append(
                        f"disagg: {tok} placed on {rec.get('peer')}, "
                        "not the prefill peer"
                    )
                readers_d[tok] = StreamReader(base2, rec["request_id"])
                readers_d[tok].start()
            for tok, reader in readers_d.items():
                reader.join(timeout=420)
                if reader.is_alive():
                    problems.append(
                        f"disagg: {tok} stream never terminated"
                    )
                elif reader.error:
                    problems.append(
                        f"disagg: {tok} stream tore: {reader.error}"
                    )
                else:
                    if reader.tokens() != refs_d[tok]:
                        problems.append(
                            f"disagg: {tok} NOT BITWISE through the "
                            "prefill->decode handoff"
                        )
                    idxs = reader.indices()
                    if idxs != list(range(len(idxs))):
                        problems.append(
                            f"disagg: {tok} client indices not "
                            f"contiguous: {idxs}"
                        )
            wait_finished(base2, rids_d, refs_d, problems, label="disagg: ")
            migrated = read_metric(base2, "fleet_handoff_disagg_total")
            if migrated < 1:
                problems.append(
                    "disagg: no prefill->decode migration landed "
                    f"(disagg={migrated}, fallbacks="
                    f"{read_metric_sum(base2, 'fleet_handoff_fallbacks_total')})"
                )

            # ---- trace leg, live surfaces: per-request attribution
            # (/v1/requestz), the raw span feed (/v1/tracez), and the
            # aggregated fleet exposition (peer-labelled /metricsz)
            probe_rid = next(iter(rids_d.values()), None)
            if probe_rid is not None:
                code, tl = http_json(
                    "GET", f"{base2}/v1/requestz/{probe_rid}"
                )
                if code != 200 or not tl.get("trace_id"):
                    problems.append(
                        f"trace leg: requestz {code}: {tl}"
                    )
                elif not tl.get("phases"):
                    problems.append(
                        f"trace leg: requestz has no phase "
                        f"attribution: {tl}"
                    )
                elif len(tl.get("processes", [])) < 2:
                    problems.append(
                        "trace leg: requestz stitched fewer than 2 "
                        f"processes: {tl.get('processes')}"
                    )
            code, tz = http_json("GET", f"{base2}/v1/tracez")
            if code != 200 or not tz.get("records"):
                problems.append(
                    f"trace leg: router tracez empty: {code}"
                )
            with urllib.request.urlopen(
                f"{base2}/metricsz", timeout=30
            ) as resp:
                fleet_text = resp.read().decode()
            if not any(
                line.startswith("daemon_") and 'peer="' in line
                for line in fleet_text.splitlines()
            ):
                problems.append(
                    "trace leg: fleet /metricsz re-exports no "
                    "peer-labelled daemon_* series"
                )
            if "fleet:" not in fleet_text:
                problems.append(
                    "trace leg: fleet /metricsz carries no fleet-level "
                    "sum series"
                )
            if "fleet_phase_seconds" not in fleet_text:
                problems.append(
                    "trace leg: no fleet_phase_seconds histogram "
                    "observed"
                )

            # ---- overhead leg: the same cold schedule through an
            # UNTRACED role-pinned router vs the traced one — tracing
            # must not tax the serve path measurably
            rnd_o = random.Random(45)

            def oh_batch(tag):
                return [
                    {
                        "dedupe_token": f"fleet-oh-{tag}-{i}",
                        "prompt": [
                            rnd_o.randrange(1, 250) for _ in range(11)
                        ],
                        "max_new_tokens": DEFAULT_NEW_TOKENS,
                    }
                    for i in range(4)
                ]

            batch_plain, batch_traced = oh_batch("p"), oh_batch("t")
            refs_oh = greedy_references(batch_plain + batch_traced)
            router2b, r2bready = spawn_router(
                tmpdir, [p.addr for p in peers],
                roles=["prefill", "decode"], name="router2b",
            )
            try:
                r2bport = wait_ready(r2bready, router2b)["port"]
                base2b = f"http://127.0.0.1:{r2bport}"

                def timed_batch(base_url, batch):
                    t0 = time.monotonic()
                    rids = {}
                    for entry in batch:
                        code, rec = http_json(
                            "POST", f"{base_url}/v1/submit", entry
                        )
                        if code == 200:
                            rids[entry["dedupe_token"]] = (
                                rec["request_id"]
                            )
                        else:
                            problems.append(
                                f"overhead submit {code}: {rec}"
                            )
                    wait_finished(
                        base_url, rids, refs_oh, problems,
                        label="overhead: ",
                    )
                    return time.monotonic() - t0

                t_plain = timed_batch(base2b, batch_plain)
                t_traced = timed_batch(base2, batch_traced)
                overhead = max(0.0, t_traced / max(t_plain, 1e-9) - 1.0)
                trace_evidence["overhead"] = {
                    "untraced_seconds": round(t_plain, 3),
                    "traced_seconds": round(t_traced, 3),
                    "ratio": round(overhead, 4),
                }
                # generous gate bound: batches this small are noisy on
                # a 1-core box; the recorded artifact carries the
                # measured ratio for the <=5% acceptance judgment
                if overhead > 0.25:
                    problems.append(
                        "trace leg: traced serve path "
                        f"{overhead:.1%} slower than untraced"
                    )
                stop_gracefully(router2b, problems, "router2b")
                router2b = None
            finally:
                if router2b is not None and router2b.poll() is None:
                    router2b.kill()
                    router2b.wait(timeout=30)

            # kill the decode peer; fresh work falls back TYPED
            peers[1].sigkill()
            code, rec = http_json("POST", f"{base2}/v1/submit", kill_entry)
            if code != 200:
                problems.append(f"disagg kill submit {code}: {rec}")
            else:
                reader = StreamReader(base2, rec["request_id"])
                reader.start()
                reader.join(timeout=420)
                if reader.is_alive() or reader.error:
                    problems.append(
                        "disagg kill: stream did not survive the dead "
                        f"decode peer (error={reader.error})"
                    )
                elif reader.tokens() != refs_d["fleet-dg-kill"]:
                    problems.append(
                        "disagg kill: colocated fallback NOT BITWISE"
                    )
            fallbacks = read_metric_sum(
                base2, "fleet_handoff_fallbacks_total"
            )
            if fallbacks < 1:
                problems.append(
                    "disagg kill: dead decode peer produced no typed "
                    f"fallback (fallbacks_total={fallbacks})"
                )
            stop_gracefully(router2, problems, "router2")
            router2 = None
        finally:
            if router2 is not None and router2.poll() is None:
                router2.kill()
                router2.wait(timeout=30)
        # bring the decode daemon back so the fleet drains gracefully
        peers[1].spawn()
        peers[1].wait_ready()

        # ---- trace leg, stitching: the three span logs -> ONE
        # Perfetto file via the CLI, then judge connectivity — every
        # disagg request must be a single-rooted trace crossing >= 2
        # pids with a cross-process parent link (the flow arrow)
        trace_problems = stitch_and_judge(
            trace_out, router2_log, peers, rids_d, trace_evidence
        )
        problems.extend(trace_problems)
        if record:
            with open(record, "w") as fh:
                json.dump(trace_evidence, fh, indent=2)
                fh.write("\n")

        # ---- graceful stop: router first, then the daemons
        stop_gracefully(router_proc, problems, "router")
        router_proc = None
        for p in peers:
            stop_gracefully(p.proc, problems, p.name)
    finally:
        for proc in [router_proc] + [p.proc for p in peers]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        if not keep and not problems:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    return problems


def read_metric_family(base, name):
    """All series of one metric family -> {label_suffix: value}."""
    with urllib.request.urlopen(f"{base}/metricsz", timeout=30) as resp:
        text = resp.read().decode()
    family = {}
    for line in text.splitlines():
        if line.startswith(name + "{") or line.startswith(name + " "):
            series, value = line.rsplit(" ", 1)
            family[series[len(name):].strip() or "_"] = float(value)
    return family


def _disagg_leg(tmpdir, label, roles, refs, burst, measured):
    """One disagg-bench leg: N daemons under the given roles, the
    seeded long-prefill burst + measured decode-heavy probes, decode
    ITL/TTFT from the probes' own relayed streams.  Returns
    (stats, problems)."""
    problems = []
    ports = pick_ports(len(roles))
    peers = [
        Peer(tmpdir, f"{label}{i}", port, role=role, tick_sleep=0.01)
        for i, (port, role) in enumerate(zip(ports, roles))
    ]
    router_proc = None
    stats = {"label": label, "roles": list(roles)}
    try:
        for p in peers:
            p.spawn()
        for p in peers:
            p.wait_ready()
        router_proc, rready = spawn_router(
            tmpdir, [p.addr for p in peers],
            roles=list(roles), name=f"router_{label}",
        )
        rport = wait_ready(rready, router_proc)["port"]
        base = f"http://127.0.0.1:{rport}"

        # the burst first (it is the prefill contention), then the
        # measured probes whose decode ITL the record judges
        rids, readers = {}, {}
        for entry in burst + measured:
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code != 200:
                problems.append(f"{label}: submit {code}: {rec}")
                continue
            tok = entry["dedupe_token"]
            rids[tok] = rec["request_id"]
            readers[tok] = StreamReader(base, rec["request_id"])
            readers[tok].start()

        measured_toks = {e["dedupe_token"] for e in measured}
        ttfts, gaps_all, gaps_steady = [], [], []
        for tok, reader in readers.items():
            reader.join(timeout=420)
            if reader.is_alive():
                problems.append(f"{label}: {tok} stream never terminated")
                continue
            if reader.error:
                problems.append(
                    f"{label}: {tok} stream tore: {reader.error}"
                )
                continue
            if reader.tokens() != refs[tok]:
                problems.append(
                    f"{label}: {tok} diverges from the greedy "
                    "reference (NOT BITWISE)"
                )
            idxs = reader.indices()
            if idxs != list(range(len(idxs))):
                problems.append(
                    f"{label}: {tok} client indices not contiguous"
                )
            if tok in measured_toks:
                if reader.ttft() is not None:
                    ttfts.append(reader.ttft())
                gaps = reader.itls()
                gaps_all.extend(gaps)
                # steady-state view: drop each stream's single largest
                # gap (the disagg leg's one-time migration stall; the
                # same trim applies to BOTH legs so the comparison
                # stays symmetric).  The stall itself is reported via
                # fleet_handoff_seconds_total.
                if gaps:
                    trimmed = sorted(gaps)[:-1]
                    gaps_steady.extend(trimmed)

        # every accepted request terminal + bitwise; retries answer the
        # original record (zero lost, zero duplicated)
        wait_finished(base, rids, refs, problems, label=f"{label}: ")
        for entry in burst + measured:
            tok = entry["dedupe_token"]
            if tok not in rids:
                continue
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code != 200 or rec.get("request_id") != rids[tok]:
                problems.append(
                    f"{label}: {tok} retry re-admitted — duplicate "
                    f"work path ({code} {rec})"
                )

        stats.update(
            requests=len(rids),
            measured=len(measured_toks),
            ttft_p95_seconds=p95(ttfts),
            decode_itl_p95_seconds=p95(gaps_steady),
            decode_itl_p95_all_gaps_seconds=p95(gaps_all),
            decode_itl_samples=len(gaps_steady),
            handoff_disagg=read_metric(
                base, "fleet_handoff_disagg_total"
            ),
            handoff_bytes=read_metric(base, "fleet_handoff_bytes_total"),
            handoff_seconds=read_metric(
                base, "fleet_handoff_seconds_total"
            ),
            handoff_fallbacks=read_metric_family(
                base, "fleet_handoff_fallbacks_total"
            ),
        )
        stop_gracefully(router_proc, problems, f"{label}-router")
        router_proc = None
        for p in peers:
            stop_gracefully(p.proc, problems, p.name)
    finally:
        for proc in [router_proc] + [p.proc for p in peers]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
    return stats, problems


def run_disagg(args):
    """1-prefill/2-decode vs 3-mixed at equal hardware, same seeded
    schedule: a long-prefill burst contends with decode-heavy probes;
    the record (FLEET_r02.json) captures decode ITL p95 / TTFT per leg
    plus the handoff byte/latency cost, and any correctness problem
    (lost, duplicated, or non-bitwise stream) fails the bench."""
    import tempfile

    seed = args.disagg
    tmpdir = args.workdir or tempfile.mkdtemp(prefix="fleet_disagg_")
    rnd = random.Random(seed ^ 0xD15A)
    # long prompts near the tiny model's seq_len: prefill compute is
    # the contention the decode pool escapes
    burst = [
        {
            "dedupe_token": f"burst-{seed}-{i}",
            "prompt": [rnd.randrange(1, 250) for _ in range(24)],
            "max_new_tokens": 4,
        }
        for i in range(8)
    ]
    measured = [
        {
            "dedupe_token": f"probe-{seed}-{i}",
            "prompt": [rnd.randrange(1, 250) for _ in range(8)],
            "max_new_tokens": HANDOFF_NEW_TOKENS,
        }
        for i in range(6)
    ]
    refs = greedy_references(burst + measured)
    baseline, problems = _disagg_leg(
        tmpdir, "mixed", ("mixed", "mixed", "mixed"),
        refs, burst, measured,
    )
    disagg, problems_b = _disagg_leg(
        tmpdir, "disagg", ("prefill", "decode", "decode"),
        refs, burst, measured,
    )
    problems += problems_b
    if disagg.get("handoff_disagg", 0) < 1:
        problems.append(
            "disagg leg: no prefill->decode migration fired "
            f"(fallbacks={disagg.get('handoff_fallbacks')})"
        )
    record = {
        "bench": "fleet_disagg",
        "seed": seed,
        "config": {
            "daemons": 3,
            "burst_requests": len(burst),
            "measured_requests": len(measured),
            "burst_prompt_tokens": 24,
            "probe_new_tokens": HANDOFF_NEW_TOKENS,
            "baseline_roles": list(baseline["roles"]),
            "disagg_roles": list(disagg["roles"]),
            "itl_note": (
                "decode_itl_p95_seconds drops each stream's single "
                "largest gap (applied to both legs); the untrimmed "
                "view is decode_itl_p95_all_gaps_seconds"
            ),
        },
        "baseline": baseline,
        "disagg": disagg,
    }
    b = baseline.get("decode_itl_p95_seconds")
    d = disagg.get("decode_itl_p95_seconds")
    if b and d:
        record["itl_p95_ratio_disagg_over_baseline"] = round(d / b, 4)
    record["problems"] = problems
    record["ok"] = not problems
    path = args.record or "FLEET_r02.json"
    with open(path, "w") as fh:
        json.dump(record, fh, indent=2)
        fh.write("\n")
    print(f"record: {path}")
    for label, leg in (("baseline", baseline), ("disagg", disagg)):
        print(
            f"  {label}: decode ITL p95 "
            f"{leg.get('decode_itl_p95_seconds')}s, TTFT p95 "
            f"{leg.get('ttft_p95_seconds')}s, migrations "
            f"{leg.get('handoff_disagg')}, handoff bytes "
            f"{leg.get('handoff_bytes')}"
        )
    if not problems:
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    return problems


def run_trial(args, seed):
    """One seeded soak trial (see the module docstring).  Returns
    (trial_record, problems)."""
    rnd = random.Random(seed ^ 0xF1EE7)
    problems = []
    tmpdir = os.path.join(
        args.workdir or "/tmp", f"fleet_soak_{os.getpid()}_{seed}"
    )
    os.makedirs(tmpdir, exist_ok=True)
    ports = pick_ports(args.daemons)
    peers = [Peer(tmpdir, f"d{i}", p) for i, p in enumerate(ports)]
    by_addr = {p.addr: p for p in peers}
    router_proc = None
    try:
        for p in peers:
            if os.path.exists(p.journal):
                os.remove(p.journal)
            p.spawn(grace=args.grace)
        for p in peers:
            p.wait_ready()
        router_proc, rready = spawn_router(
            tmpdir, [p.addr for p in peers],
            warm_blocks=args.warm_blocks,
        )
        rport = wait_ready(rready, router_proc)["port"]
        base = f"http://127.0.0.1:{rport}"

        # every schedule this trial runs, referenced in one pass: two
        # shared-prefix traffic groups, the kill leg (fillers pin the
        # victim's slots behind one long target), and a downtime group
        # served while the victim is dead (warm-start freight its own
        # journal replay provably cannot recover)
        prefix_a = shared_prefix(seed)
        prefix_b = shared_prefix(seed + 1)
        prefix_c = shared_prefix(seed + 2)
        prefix_d = shared_prefix(seed + 3)
        half = args.requests // 2
        schedule = (
            make_schedule(seed, half, args.new, prefix=prefix_a)
            + make_schedule(
                seed + 1000, args.requests - half, args.new,
                prefix=prefix_b,
            )
        )
        fillers = [
            {
                "dedupe_token": f"fleet-{seed}-fill-{i}",
                "prompt": prefix_c + [
                    rnd.randrange(1, 250) for _ in range(2)
                ],
                "max_new_tokens": HANDOFF_NEW_TOKENS,
            }
            for i in range(5)
        ]
        target = {
            "dedupe_token": f"fleet-{seed}-target",
            "prompt": prefix_c + [
                rnd.randrange(1, 250) for _ in range(2)
            ],
            "max_new_tokens": HANDOFF_NEW_TOKENS,
        }
        downtime = make_schedule(
            seed + 2000, 2, args.new, prefix=prefix_d
        )
        refs = greedy_references(
            schedule + fillers + [target] + downtime
        )

        # ---- phase 1: streamed traffic through a healthy fleet
        rids = {}
        readers = {}
        for entry in schedule:
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code != 200:
                problems.append(f"submit {code}: {rec}")
                continue
            tok = entry["dedupe_token"]
            rids[tok] = rec["request_id"]
            readers[tok] = StreamReader(base, rec["request_id"])
            readers[tok].start()
        accepted = len(rids)
        for tok, reader in readers.items():
            reader.join(timeout=420)
            if reader.is_alive():
                problems.append(f"{tok}: relay stream never terminated")
            elif reader.error:
                problems.append(f"{tok}: relay tore: {reader.error}")
            elif reader.tokens() != refs[tok]:
                problems.append(
                    f"{tok}: stream diverges from the greedy reference"
                )
        wait_finished(base, rids, refs, problems)

        # ---- the seeded kill: the fillers share the target's prefix,
        # so the ring packs them onto one daemon and keeps the target
        # mid-flight behind them; that daemon is the victim
        fill_rids = {}
        for entry in fillers:
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code != 200:
                problems.append(f"filler submit {code}: {rec}")
                continue
            fill_rids[entry["dedupe_token"]] = rec["request_id"]
        code, rec = http_json("POST", f"{base}/v1/submit", target)
        if code != 200:
            problems.append(f"target submit {code}: {rec}")
            raise RuntimeError("kill-leg target never admitted")
        rid_target = rec["request_id"]
        accepted += len(fill_rids) + 1
        victim = by_addr[rec["peer"]]
        reader = StreamReader(base, rid_target)
        reader.start()
        kill_when_mid_flight(reader, victim, problems)
        kill_at = time.monotonic()
        reader.join(timeout=420)
        if reader.is_alive():
            problems.append("kill leg: relay stream never terminated")
        elif reader.error:
            problems.append(f"kill leg: relay tore: {reader.error}")
        else:
            idxs = reader.indices()
            if idxs != list(range(len(idxs))):
                problems.append(
                    f"kill leg: client indices not contiguous: {idxs}"
                )
            if reader.tokens() != refs[target["dedupe_token"]]:
                problems.append(
                    "kill leg: handed-off stream diverges from the "
                    "greedy reference (NOT BITWISE)"
                )
        code, target_rec = http_json(
            "GET", f"{base}/v1/result/{rid_target}"
        )
        if code != 200 or target_rec.get("handoffs", 0) < 1:
            problems.append(
                f"kill leg: no handoff recorded on the target: "
                f"{target_rec}"
            )
        kill_finished = wait_finished(
            base, fill_rids, refs, problems, label="filler: "
        )
        handoffs = sum(
            r.get("handoffs", 0)
            for r in [target_rec] + list(kill_finished.values())
            if isinstance(r, dict)
        )
        kill_to_done = round(time.monotonic() - kill_at, 3)

        # ---- fleet-wide idempotency: a full client retry sweep maps
        # every dedupe token back to its original request id, across
        # the host death
        all_rids = dict(rids)
        all_rids.update(fill_rids)
        all_rids[target["dedupe_token"]] = rid_target
        dedupe_hits = 0
        for entry in schedule + fillers + [target]:
            tok = entry["dedupe_token"]
            if tok not in all_rids:
                continue
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code == 200 and rec["request_id"] == all_rids[tok]:
                dedupe_hits += 1
            else:
                problems.append(
                    f"{tok}: retry re-admitted as {rec.get('request_id')}"
                    f" != {all_rids[tok]} — duplicate completion path"
                )

        # ---- downtime traffic: hot chains the dead victim never saw
        d_rids = {}
        d_peers = set()
        for entry in downtime:
            code, rec = http_json("POST", f"{base}/v1/submit", entry)
            if code != 200:
                problems.append(f"downtime submit {code}: {rec}")
                continue
            d_rids[entry["dedupe_token"]] = rec["request_id"]
            d_peers.add(rec["peer"])
        wait_finished(base, d_rids, refs, problems, label="downtime: ")
        accepted += len(d_rids)

        # ---- corrupt-injection leg against a survivor
        survivor = next(p for p in peers if p is not victim)
        wire_reason = corrupt_import_leg(
            survivor.addr, survivor.addr, seed, problems
        )

        # ---- restart the victim; the router warm-starts it remotely.
        # The router's donor pick (the newcomer's ring successor) may
        # hold only chains the victim's own journal replay already
        # recovered — then every verdict is `already_cached` and the
        # deterministic fallback ships the downtime peer's chains
        # directly instead.
        victim.spawn(grace=args.grace)
        victim.wait_ready()
        imported = wait_metric(
            base, 'fleet_kv_imports_total{status="imported"}', 1,
            timeout=20,
        )
        if imported < 1:
            imported = direct_import_leg(
                d_peers, victim.addr, problems
            )

        # ---- graceful stop
        stop_gracefully(router_proc, problems, "router")
        router_proc = None
        for p in peers:
            stop_gracefully(p.proc, problems, p.name, grace=args.grace + 60)
        trial = {
            "seed": seed,
            "victim": victim.addr,
            "accepted": accepted,
            "requests": args.requests,
            "finished": len(all_rids) + len(d_rids) - sum(
                1 for p in problems if "lost accepted work" in p
            ),
            "handoffs": handoffs,
            "dedupe_hits_on_retry": dedupe_hits,
            "kv_imported": imported,
            "corrupt_refusal_reason": wire_reason,
            "kill_to_done_seconds": kill_to_done,
        }
    finally:
        for proc in [router_proc] + [p.proc for p in peers]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        if not problems:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    return trial, problems


def run_soak(args):
    """The seeded host-kill acceptance soak (>= 3 seeds)."""
    record = {"bench": "fleet_soak", "trials": []}
    problems = []
    total_handoffs = 0
    for trial in range(args.trials):
        seed = args.soak + trial
        trial_rec, trial_problems = run_trial(args, seed)
        trial_rec["problems"] = list(trial_problems)
        record["trials"].append(trial_rec)
        problems.extend(trial_problems)
        total_handoffs += trial_rec.get("handoffs", 0)
        print(
            f"trial {trial} (seed {seed}): victim={trial_rec['victim']} "
            f"finished={trial_rec['finished']}/{trial_rec['accepted']} "
            f"handoffs={trial_rec['handoffs']} "
            f"kv_imported={trial_rec['kv_imported']} "
            f"corrupt_refusal={trial_rec['corrupt_refusal_reason']} "
            f"problems={len(trial_problems)}"
        )
    if total_handoffs == 0:
        problems.append(
            "no trial handed a request across hosts — the soak proved "
            "nothing about cross-host continuation; lengthen --new or "
            "add trials"
        )
    record["handoffs_total"] = total_handoffs
    record["ok"] = not problems
    if args.record:
        with open(args.record, "w") as fh:
            json.dump(record, fh, indent=2)
            fh.write("\n")
        print(f"record: {args.record}")
    return problems


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true",
                    help="INTERNAL: run one daemon child")
    ap.add_argument("--route", action="store_true",
                    help="INTERNAL: run the fleet router child")
    ap.add_argument("--smoke", action="store_true",
                    help="fast gate: router + 2 daemons, one SIGKILL, "
                         "bitwise handoff, one warm start, one corrupt "
                         "refusal")
    ap.add_argument("--soak", type=int, default=None, metavar="SEED",
                    help="seeded host-kill soak: trials use seeds "
                         "SEED..SEED+trials-1")
    ap.add_argument("--disagg", type=int, default=None, metavar="SEED",
                    help="prefill/decode disaggregation bench: "
                         "1-prefill/2-decode vs 3-mixed at equal "
                         "hardware, records FLEET_r02.json")
    ap.add_argument("--peers", type=str, default="")
    ap.add_argument("--role", type=str, default="mixed",
                    help="INTERNAL (--serve): this daemon's fleet role")
    ap.add_argument("--tick-sleep", type=float, default=0.0,
                    help="INTERNAL (--serve): seconds slept per pump "
                         "tick — paces the tiny model like a real one")
    ap.add_argument("--roles", type=str, default="",
                    help="INTERNAL (--route): comma roles aligned "
                         "with --peers")
    ap.add_argument("--journal", type=str, default="")
    ap.add_argument("--ready-file", type=str, default="")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--grace", type=float, default=60.0)
    ap.add_argument("--fsync-batch", type=int, default=8)
    ap.add_argument("--warm-blocks", type=int, default=64)
    ap.add_argument("--daemons", type=int, default=3)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--new", type=int, default=12)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--workdir", type=str, default="")
    ap.add_argument("--record", type=str, default="")
    ap.add_argument("--trace-log", type=str, default="",
                    help="arm tracing in a child (--serve/--route): "
                         "spool spans to this JSONL, served at "
                         "/v1/tracez")
    ap.add_argument("--trace-out", type=str, default="",
                    help="smoke/disagg: write the stitched Perfetto "
                         "trace here (also enables the trace leg)")
    args = ap.parse_args()

    if args.serve:
        if not args.journal or not args.ready_file:
            ap.error("--serve needs --journal and --ready-file")
        sys.exit(serve(args))
    if args.route:
        if not args.peers or not args.ready_file:
            ap.error("--route needs --peers and --ready-file")
        sys.exit(route(args))
    if args.smoke:
        problems = run_smoke(
            trace_out=args.trace_out, record=args.record,
        )
    elif args.soak is not None:
        problems = run_soak(args)
    elif args.disagg is not None:
        problems = run_disagg(args)
    else:
        ap.error("pick a mode: --smoke, --soak SEED, or --disagg SEED")
        return
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(
            f"fleet_bench: {len(problems)} INVARIANT VIOLATION(S)",
            file=sys.stderr,
        )
        sys.exit(1)
    print("fleet_bench: OK")


if __name__ == "__main__":
    main()
