"""Iteration-level admission scheduling for the serving engine.

Orca-style continuous batching separates two decisions the static path
fuses: WHEN a request joins the batch (admission — here) and WHEN it
leaves (retirement — per-slot EOS/length checks in the engine).  The
scheduler owns the first: a FIFO queue with three policy knobs —

- ``max_queue``: admission control.  A full queue REJECTS new requests at
  submission instead of growing without bound (the backpressure signal a
  front-end needs).
- ``max_prefills_per_tick``: prefill/decode interleaving.  Each prefill
  runs a full prompt forward between decode ticks, stalling every running
  request's next token; capping admissions per tick bounds that
  head-of-line latency hit (1 = smoothest inter-token latency, higher =
  faster queue drain).  With the engine's bucketed prefill the whole
  admission set runs as ONE batched call, so higher values also amortize
  per-call overhead instead of multiplying it.
- ``max_wait``: queue timeout.  Requests that cannot reach a slot within
  ``max_wait`` seconds EXPIRE (dropped with status ``expired``) rather
  than serving a reply the client already abandoned.

Time is injectable: ``clock`` (default ``time.monotonic``) supplies "now"
whenever a caller does not pass it explicitly, so queue-timeout tests run
deterministically on a fake clock instead of sleeping.

Telemetry: given a ``registry``
(:class:`~tpu_parallel.obs.registry.MetricRegistry` — the engine wires
its own in), every ``schedule()`` call publishes the
``serving_queue_age_seconds`` gauge (how long the OLDEST queued request
has waited — the head-of-line latency a new arrival is behind) and
observes each admitted request's queue wait into the
``serving_queue_wait_seconds`` histogram.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, List, Optional

from tpu_parallel.serving.request import (
    EXPIRED,
    QUEUED,
    REJECT_DRAINING,
    REJECT_QUEUE_FULL,
    RequestOutput,
)


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    max_queue: Optional[int] = None  # None = unbounded queue
    max_prefills_per_tick: int = 1
    max_wait: Optional[float] = None  # seconds; None = wait forever


class SubmitResult:
    """Typed admission verdict: truthy on accept, falsy on reject with a
    machine-readable ``reason`` (``REJECT_QUEUE_FULL`` / ``REJECT_DRAINING``).

    Replaces the PR-1 bare bool so callers — the engine surfacing
    ``RequestOutput.finish_reason``, the cluster frontend deciding whether
    to try another replica — see WHY admission refused, not just that it
    did.  Still usable exactly like the old bool (``if not submit(...)``).
    """

    __slots__ = ("reason",)

    ACCEPTED: "SubmitResult"

    def __init__(self, reason: Optional[str] = None):
        self.reason = reason

    def __bool__(self) -> bool:
        return self.reason is None

    def __repr__(self) -> str:
        return (
            "SubmitResult(accepted)"
            if self.reason is None
            else f"SubmitResult(rejected: {self.reason})"
        )


SubmitResult.ACCEPTED = SubmitResult()


class FIFOScheduler:
    """First-come-first-served admission with the policy knobs above.

    The engine calls ``submit`` at ``add_request`` time, then once per
    tick: ``expire(now)`` to drop timed-out entries, and
    ``schedule(n_free, now)`` to pop the tick's admissions.  ``now``
    defaults to the scheduler's own ``clock`` when omitted.
    """

    def __init__(
        self,
        config: Optional[SchedulerConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        self.config = config or SchedulerConfig()
        if self.config.max_prefills_per_tick < 1:
            raise ValueError(
                f"max_prefills_per_tick="
                f"{self.config.max_prefills_per_tick} < 1"
            )
        self.clock = clock
        self.registry = registry
        self._queue: deque = deque()
        # drain gate: True refuses NEW submissions (typed REJECT_DRAINING)
        # while already-queued entries keep admitting — set by
        # ``begin_drain()`` for graceful shutdown / replica retirement
        self.draining = False

    @property
    def depth(self) -> int:
        return len(self._queue)

    @property
    def pending_prefill_tokens(self) -> int:
        """Total prompt tokens waiting in the queue — the prefill work a
        new admission is behind (the cluster router's least-loaded signal,
        alongside queue depth and active slots)."""
        return sum(len(out.request.prompt) for out in self._queue)

    def queued(self) -> List[RequestOutput]:
        """Snapshot of the queue in FIFO order (no mutation)."""
        return list(self._queue)

    def oldest_age(self, now: Optional[float] = None) -> float:
        """Seconds the head-of-queue request has waited (0.0 when empty
        or the head has no arrival time)."""
        if not self._queue:
            return 0.0
        arrival = self._queue[0].arrival_time
        if arrival is None:
            return 0.0
        if now is None:
            now = self.clock()
        return max(0.0, now - arrival)

    def _observe(self, now: float, admitted: List[RequestOutput]) -> None:
        """Publish the queue-age gauge + admitted queue waits (no-op
        without a registry)."""
        if self.registry is None:
            return
        self.registry.gauge("serving_queue_age_seconds").set(
            self.oldest_age(now)
        )
        wait = self.registry.histogram("serving_queue_wait_seconds")
        for out in admitted:
            if out.arrival_time is not None:
                wait.observe(max(0.0, now - out.arrival_time))

    def submit(self, out: RequestOutput, requeue: bool = False) -> SubmitResult:
        """Enqueue; a falsy :class:`SubmitResult` carrying the typed reason
        when admission control refuses (queue full / draining).

        ``requeue=True`` marks accepted work being RELOCATED (the cluster
        frontend re-routing a draining or dead replica's queue) rather than
        new work — it bypasses the drain gate, never the queue bound.
        """
        cfg = self.config
        if self.draining and not requeue:
            return SubmitResult(REJECT_DRAINING)
        if cfg.max_queue is not None and len(self._queue) >= cfg.max_queue:
            return SubmitResult(REJECT_QUEUE_FULL)
        out.status = QUEUED
        self._queue.append(out)
        return SubmitResult.ACCEPTED

    def retune(
        self,
        max_prefills_per_tick: Optional[int] = None,
        max_queue: Optional[int] = None,
    ) -> SchedulerConfig:
        """Adjust admission policy knobs on a LIVE scheduler — the
        cluster autopilot's retuning hook.  Only the named knobs change
        (``max_queue`` cannot be retuned back to unbounded: None means
        "leave it alone"); the same validation as construction applies.
        Queued entries are untouched — a tightened ``max_queue`` below
        the current depth simply refuses new work until the queue drains
        under it.  Returns the new (frozen) config."""
        cfg = self.config
        if max_prefills_per_tick is not None:
            if max_prefills_per_tick < 1:
                raise ValueError(
                    f"max_prefills_per_tick={max_prefills_per_tick} < 1"
                )
            cfg = dataclasses.replace(
                cfg, max_prefills_per_tick=max_prefills_per_tick
            )
        if max_queue is not None:
            if max_queue < 0:
                raise ValueError(f"max_queue={max_queue} < 0")
            cfg = dataclasses.replace(cfg, max_queue=max_queue)
        self.config = cfg
        return cfg

    def begin_drain(self) -> None:
        """Close the admission gate: subsequent ``submit()`` calls reject
        with ``REJECT_DRAINING``; queued entries still schedule."""
        self.draining = True

    def take_queued(self) -> List[RequestOutput]:
        """Remove and return EVERY queued entry (FIFO order, status left
        QUEUED) — the drain/failover path that re-routes a replica's
        queued remainder to its peers."""
        taken = list(self._queue)
        self._queue.clear()
        return taken

    def remove(self, request_id: str) -> Optional[RequestOutput]:
        """Pull one queued entry by request id (cancellation before the
        request ever reached a slot); None when not queued here."""
        for out in self._queue:
            if out.request.request_id == request_id:
                self._queue.remove(out)
                return out
        return None

    def expire(self, now: Optional[float] = None) -> List[RequestOutput]:
        """Drop queued entries older than ``max_wait``; returns them."""
        if self.config.max_wait is None:
            return []
        if now is None:
            now = self.clock()
        expired = []
        kept = deque()
        for out in self._queue:
            arrival = out.arrival_time if out.arrival_time is not None else now
            waited = now - arrival
            if waited > self.config.max_wait:
                out.status = EXPIRED
                expired.append(out)
            else:
                kept.append(out)
        self._queue = kept
        return expired

    def schedule(
        self,
        n_free: int,
        now: Optional[float] = None,
        bucket_key: Optional[Callable[[RequestOutput], object]] = None,
        can_admit: Optional[Callable[[RequestOutput], bool]] = None,
    ) -> List[RequestOutput]:
        """Pop up to ``min(n_free, max_prefills_per_tick)`` admissions.

        ``now`` feeds the telemetry (queue-age gauge, admitted queue
        waits); FIFO ordering itself ignores it — priority policies
        would not.  ``can_admit`` (the paged engine's estimated-blocks
        gate) vetoes individual admissions beyond the free-slot count:
        a vetoed HEAD blocks the whole tick (head-of-line, FIFO-fair —
        blocks free up as running requests retire), a vetoed non-head
        candidate is kept in place while later same-bucket entries may
        still admit.  ``bucket_key`` (the engine's bucketed-prefill
        grouping) constrains
        the tick's admissions to ONE batchable group: the FIFO head always
        admits, and the rest of the budget fills with later queued entries
        sharing the head's key — those jump ahead of earlier entries in
        OTHER buckets (bounded unfairness: a request can be overtaken only
        while the head of the queue, which admits this tick regardless,
        shares a bucket with someone behind it).  The engine runs the
        returned set as one padded batched prefill call.  Chunked
        prompts are a group like any other, but WHICH group depends on
        the engine's tick model: the per-phase engine keys them
        uniquely (one chunk start per tick — each start is its own
        batch-1 dispatch), while the unified ragged tick keys them all
        ``("chunk",)`` so several long prompts claim slots in one tick
        and ride the same fixed-shape chunk-phase dispatch.
        """
        if now is None:
            now = self.clock()
        n = min(n_free, self.config.max_prefills_per_tick)
        if n <= 0 or not self._queue:
            self._observe(now, [])
            return []
        if bucket_key is None:
            admitted = []
            while n > 0 and self._queue:
                if can_admit is not None and not can_admit(self._queue[0]):
                    break  # head-of-line: wait for blocks to free up
                admitted.append(self._queue.popleft())
                n -= 1
            self._observe(now, admitted)
            return admitted
        if can_admit is not None and not can_admit(self._queue[0]):
            self._observe(now, [])
            return []
        head = self._queue.popleft()
        admitted, key = [head], bucket_key(head)
        kept = deque()
        for out in self._queue:
            if (
                len(admitted) < n
                and bucket_key(out) == key
                and (can_admit is None or can_admit(out))
            ):
                admitted.append(out)
            else:
                kept.append(out)
        self._queue = kept
        self._observe(now, admitted)
        return admitted
