"""SSD-backed third KV tier: per-block-CRC'd blobs + a persisted radix
manifest, so the prefix cache survives a restart.

The PR 12 hierarchy (``serving/kv_hierarchy.py``) ends at host RAM and
dies with the process; million-user prefix working sets (system
prompts, few-shot preambles, RAG boilerplate) are bigger than RAM and
live longer than a deploy.  This module is the tier UNDER the host
offload tier:

- **Blobs.** Each disk-resident radix node is one file
  (``b<N>.kvw``) holding exactly one :class:`~tpu_parallel.serving.
  kv_hierarchy.KVPrefixExport` frame in the ``kv_wire`` encoding — the
  SAME self-checksummed format the fleet ships over the network, so
  damage detection and typed refusals on the disk path are the code
  the wire path already proves.  The export's ``tokens`` carry the
  FULL root-to-node chain (payload = the node's one block), which is
  what makes a cold restart able to rebuild the tree from files alone.
- **Manifest.** ``manifest.jsonl`` records which chains live on disk
  (``kv_put`` / ``kv_del``), managed by the daemon's
  :class:`~tpu_parallel.daemon.journal.JournalWriter` — per-record
  CRC32, monotone seqs, torn-tail truncation, and crash-safe
  ``rotate()`` compaction come for free and behave EXACTLY like the
  request journal under the same seeded faults.
- **Fault domain.** Every byte in or out routes through
  :mod:`~tpu_parallel.daemon.iofaults` (``scripts/check_io.py`` fences
  this file), so ``daemon_bench``'s seeded bit rot / EIO / ENOSPC land
  on the verify-or-recompute path; failures surface as typed
  :class:`KVDiskError` and feed the hierarchy's disk breaker.

The store is deliberately DUMB: it maps blob ids to verified exports
and keeps the manifest truthful.  Eviction policy, the breaker, the
prefix-closure invariant (every disk chain restorable from block 0)
and restart seeding live in ``RadixPrefixCache`` — the store never
decides what is hot.
"""

from __future__ import annotations

import dataclasses
import errno
import os
from typing import Callable, Dict, List, Optional, Tuple

from ..daemon import iofaults
from ..daemon.journal import JournalCorrupt, JournalWriter, read_journal
from .kv_hierarchy import KVPrefixExport
from .kv_wire import (
    WIRE_INTEGRITY,
    WIRE_REASONS,
    WIRE_TRUNCATED,
    WireFormatError,
    decode_exports,
    encode_export,
)

MANIFEST_NAME = "manifest.jsonl"
BLOB_SUFFIX = ".kvw"

# manifest record kinds — unknown to the daemon's recovery scan by
# design (read_journal passes unrecognized kinds through untouched)
REC_KV_PUT = "kv_put"
REC_KV_DEL = "kv_del"

# typed failure vocabulary: the wire format's reasons (a rotted blob
# refuses exactly like a rotted network frame) plus the disk-only
# shapes.  Pinned by tests — breaker accounting and bench legs key on
# these strings.
DISK_IO_ERROR = "io_error"
DISK_ENOSPC = "enospc"
DISK_MISSING = "missing_blob"
DISK_WEIGHTS = "weights_version"
DISK_CAPACITY = "capacity"
DISK_MANIFEST = "manifest_corrupt"
DISK_REASONS = WIRE_REASONS + (
    DISK_IO_ERROR,
    DISK_ENOSPC,
    DISK_MISSING,
    DISK_WEIGHTS,
    DISK_CAPACITY,
    DISK_MANIFEST,
)


class KVDiskError(RuntimeError):
    """A disk-tier operation that cannot be trusted — carries the typed
    ``reason`` (one of :data:`DISK_REASONS`) the hierarchy counts and
    the breaker feeds on.  Corrupted or unreadable bytes NEVER serve;
    the caller recomputes bitwise."""

    def __init__(self, reason: str, detail: str):
        assert reason in DISK_REASONS, reason
        super().__init__(f"{reason}: {detail}")
        self.reason = reason
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class DiskEntry:
    """One manifest-recorded blob: the token chain it restores, the
    block CRC recorded at spill (cross-checked against the decoded
    frame, so a self-consistent but WRONG blob still refuses), the
    weight set it was computed under, and its payload size."""

    blob: int
    tokens: Tuple[int, ...]
    crc: int
    weights_version: str
    nbytes: int


class KVDiskStore:
    """Blob files + journal-backed manifest under one directory.

    ``clock`` is injectable (``scripts/check_clock.py`` fences wall
    time in serving) — it stamps manifest records and drives
    ``manifest_age_seconds``.  ``capacity_blocks`` bounds resident
    blobs; the HIERARCHY evicts to make room (the store just refuses
    past the line, typed ``capacity``)."""

    def __init__(
        self,
        root: str,
        clock: Callable[[], float],
        *,
        capacity_blocks: int,
        fsync_batch: int = 8,
        compact_min_records: int = 64,
        compact_factor: int = 4,
    ):
        if capacity_blocks < 1:
            raise ValueError(f"capacity_blocks={capacity_blocks} < 1")
        os.makedirs(root, exist_ok=True)
        self.root = root
        self.clock = clock
        self.capacity_blocks = capacity_blocks
        self.compact_min_records = compact_min_records
        self.compact_factor = compact_factor
        self.manifest_path = os.path.join(root, MANIFEST_NAME)
        # lifetime tallies (this process)
        self.puts = 0
        self.deletes = 0
        self.loads = 0
        self.manifest_errors = 0
        self.swept_blobs = 0
        # non-None when construction found mid-file manifest damage:
        # the typed reason we reset on (serving is unaffected — the
        # disk tier is a cache, an untrustworthy index starts empty)
        self.manifest_reset_reason: Optional[str] = None
        self._entries: Dict[int, DiskEntry] = {}
        next_seq = self._fold_manifest()
        self._writer = JournalWriter(
            self.manifest_path,
            clock,
            fsync_batch=fsync_batch,
            next_seq=next_seq,
        )
        self._sweep_unreferenced()
        self._next_blob = 1 + max(self._entries, default=-1)
        self._last_append = clock()

    # -- construction helpers ------------------------------------------------

    def _fold_manifest(self) -> int:
        """Replay the manifest into ``_entries``.  Tail damage is
        tolerated (the journal reader truncates it); MID-file damage is
        a manifest that lies — typed reset to empty, old file removed
        so the fresh writer does not weld onto garbage."""
        if not os.path.exists(self.manifest_path):
            return 0
        try:
            records, _torn = read_journal(self.manifest_path)
        except JournalCorrupt as err:
            self.manifest_reset_reason = err.reason
            self.manifest_errors += 1
            os.remove(self.manifest_path)
            return 0
        for rec in records:
            kind = rec.get("record")
            if kind == REC_KV_PUT:
                try:
                    entry = DiskEntry(
                        blob=int(rec["blob"]),
                        tokens=tuple(int(t) for t in rec["tokens"]),
                        crc=int(rec["bcrc"]),
                        weights_version=str(rec["wv"]),
                        nbytes=int(rec.get("nbytes", 0)),
                    )
                except (KeyError, TypeError, ValueError):
                    # a CRC-valid record with a broken schema is a
                    # writer bug, not media rot — drop just the record
                    self.manifest_errors += 1
                    continue
                self._entries[entry.blob] = entry
            elif kind == REC_KV_DEL:
                self._entries.pop(rec.get("blob"), None)
        return records[-1]["seq"] + 1 if records else 0

    def _sweep_unreferenced(self) -> None:
        """Reconcile directory against manifest, both directions: a
        blob without a record is a torn put (the crash hit between
        blob fsync and manifest append) — garbage, removed; a record
        without a blob is a torn delete — the entry drops and a
        ``kv_del`` makes the manifest truthful again."""
        resident = set()
        for name in os.listdir(self.root):
            if not (name.startswith("b") and name.endswith(BLOB_SUFFIX)):
                continue
            try:
                blob = int(name[1 : -len(BLOB_SUFFIX)])
            except ValueError:
                continue
            resident.add(blob)
            if blob not in self._entries:
                try:
                    os.remove(os.path.join(self.root, name))
                    self.swept_blobs += 1
                except OSError:
                    pass
        for blob in [b for b in self._entries if b not in resident]:
            del self._entries[blob]
            self.swept_blobs += 1
            try:
                self._writer.append({"record": REC_KV_DEL, "blob": blob})
            except OSError:
                self.manifest_errors += 1

    # -- the three operations ------------------------------------------------

    def _blob_path(self, blob: int) -> str:
        return os.path.join(self.root, f"b{blob}{BLOB_SUFFIX}")

    def put(
        self,
        export: KVPrefixExport,
        chain_tokens: Tuple[int, ...],
    ) -> int:
        """Persist a one-block export; returns its blob id.

        ``export`` is a standard single-block ``kv_wire`` frame (its
        ``tokens`` are the node's own run); ``chain_tokens`` is the
        FULL root-to-node token chain the manifest records — what lets
        a cold restart rebuild the tree before reading any blob.  Order
        is blob-then-manifest with an fsync between, so every recorded
        entry has durable bytes behind it and a crash between the two
        leaves only a sweepable orphan file.  Raises typed
        :class:`KVDiskError` with the blob guaranteed absent."""
        if export.n_blocks != 1:
            raise ValueError(
                f"disk tier spills one block per blob, got "
                f"{export.n_blocks}"
            )
        if not export.checksums:
            raise ValueError("disk tier requires checksummed exports")
        chain_tokens = tuple(int(t) for t in chain_tokens)
        bt = export.block_tokens
        if (
            not chain_tokens
            or len(chain_tokens) % bt
            or chain_tokens[-bt:] != tuple(int(t) for t in export.tokens)
        ):
            raise ValueError(
                "chain_tokens must be a non-empty block multiple ending "
                "in the export's own run"
            )
        if len(self._entries) >= self.capacity_blocks:
            raise KVDiskError(
                DISK_CAPACITY,
                f"{len(self._entries)}/{self.capacity_blocks} blobs "
                "resident — evict before spilling",
            )
        if self._writer.wedged:
            raise KVDiskError(DISK_IO_ERROR, "manifest wedged")
        blob = self._next_blob
        self._next_blob += 1
        path = self._blob_path(blob)
        data = encode_export(export)
        try:
            fh = iofaults.open_file(path, "wb")
            try:
                iofaults.write_line(fh, data)
                fh.flush()
                iofaults.fsync_file(fh)
            finally:
                fh.close()
        except OSError as err:
            self._remove_blob(path)
            raise KVDiskError(self._os_reason(err), str(err)) from err
        try:
            self._writer.append({
                "record": REC_KV_PUT,
                "blob": blob,
                "tokens": list(chain_tokens),
                "bcrc": int(export.checksums[0]),
                "wv": export.weights_version,
                "nbytes": int(export.payload_bytes),
            })
        except OSError as err:
            self._remove_blob(path)
            self.manifest_errors += 1
            raise KVDiskError(self._os_reason(err), str(err)) from err
        self._entries[blob] = DiskEntry(
            blob=blob,
            tokens=chain_tokens,
            crc=int(export.checksums[0]),
            weights_version=export.weights_version,
            nbytes=int(export.payload_bytes),
        )
        self.puts += 1
        self._last_append = self.clock()
        self._maybe_compact()
        return blob

    def load(self, blob: int) -> KVPrefixExport:
        """Read + verify one blob.  Three layers must agree before any
        byte serves: the frame's own block CRCs (``decode_exports``
        with ``verify=True``), the manifest's recorded CRC (so a
        self-consistent but swapped blob refuses), and the recorded
        token chain.  Any disagreement is a typed refusal — the caller
        drops the subtree and recomputes bitwise."""
        entry = self._entries.get(blob)
        if entry is None:
            raise KVDiskError(DISK_MISSING, f"blob {blob} not in manifest")
        path = self._blob_path(blob)
        try:
            exports = decode_exports(iofaults.read_bytes(path), verify=True)
        except FileNotFoundError as err:
            raise KVDiskError(DISK_MISSING, str(err)) from err
        except OSError as err:
            raise KVDiskError(self._os_reason(err), str(err)) from err
        except WireFormatError as err:
            raise KVDiskError(err.reason, err.detail) from err
        if len(exports) != 1:
            raise KVDiskError(
                WIRE_TRUNCATED,
                f"blob {blob} holds {len(exports)} frames, expected 1",
            )
        export = exports[0]
        if (
            export.length > len(entry.tokens)
            or tuple(int(t) for t in export.tokens)
            != entry.tokens[len(entry.tokens) - export.length :]
        ):
            # the blob's run must be the recorded chain's tail — a
            # self-consistent but SWAPPED blob refuses here
            raise KVDiskError(
                WIRE_INTEGRITY,
                f"blob {blob} token run disagrees with manifest chain",
            )
        if not export.checksums or int(export.checksums[0]) != entry.crc:
            raise KVDiskError(
                WIRE_INTEGRITY,
                f"blob {blob} CRC disagrees with manifest",
            )
        self.loads += 1
        return export

    def delete(self, blob: int) -> None:
        """Drop a blob + its manifest entry.  Idempotent; a manifest
        append failure here is tallied, not raised — the boot-time
        sweep reconciles either half-state."""
        entry = self._entries.pop(blob, None)
        if entry is None:
            return
        self._remove_blob(self._blob_path(blob))
        try:
            self._writer.append({"record": REC_KV_DEL, "blob": blob})
        except OSError:
            self.manifest_errors += 1
        self.deletes += 1
        self._last_append = self.clock()
        self._maybe_compact()

    # -- compaction ----------------------------------------------------------

    def _maybe_compact(self) -> None:
        """Rotate once the segment carries ``compact_factor`` records
        per live entry (floored at ``compact_min_records``): restart
        fold then reads O(live) records instead of O(churn)."""
        threshold = max(
            self.compact_min_records,
            self.compact_factor * max(1, len(self._entries)),
        )
        if self._writer.records_since_rotate < threshold:
            return
        self.compact()

    def compact(self) -> None:
        """Journal-style rotation: the snapshot is the live put set.
        Crash-safe at every point (sidecar then atomic replace; an
        orphan sidecar is discarded at the next construction)."""
        snapshot = [
            {
                "record": REC_KV_PUT,
                "blob": e.blob,
                "tokens": list(e.tokens),
                "bcrc": e.crc,
                "wv": e.weights_version,
                "nbytes": e.nbytes,
            }
            for e in sorted(self._entries.values(), key=lambda e: e.blob)
        ]
        try:
            self._writer.rotate(snapshot)
        except OSError:
            self.manifest_errors += 1

    # -- accounting ----------------------------------------------------------

    @staticmethod
    def _os_reason(err: OSError) -> str:
        return DISK_ENOSPC if err.errno == errno.ENOSPC else DISK_IO_ERROR

    @staticmethod
    def _remove_blob(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, blob: int) -> bool:
        return blob in self._entries

    @property
    def blocks_in_use(self) -> int:
        return len(self._entries)

    @property
    def payload_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    @property
    def manifest_records(self) -> int:
        """Appends this process — with :attr:`manifest_compactions`,
        the docs/11 ``serving_kv_disk_manifest_*`` pair."""
        return self._writer.records

    @property
    def manifest_compactions(self) -> int:
        return self._writer.rotations

    @property
    def wedged(self) -> bool:
        return self._writer.wedged

    def manifest_age_seconds(self) -> float:
        """Seconds since the last manifest append (construction counts
        — a freshly folded manifest is as fresh as its fold)."""
        return max(0.0, self.clock() - self._last_append)

    def entries(self) -> List[DiskEntry]:
        """Live entries, shortest chain first — the order restart
        seeding wants (a node's ancestors fold before it)."""
        return sorted(
            self._entries.values(), key=lambda e: (len(e.tokens), e.blob)
        )

    def sync(self) -> None:
        self._writer.sync()

    def close(self) -> None:
        self._writer.close()
