from tpu_parallel.runtime.bootstrap import (
    enable_compilation_cache,
    initialize,
    is_simulated,
    process_info,
    simulate_cpu_devices,
)
from tpu_parallel.runtime.mesh import (
    AXIS_ORDER,
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    MeshConfig,
    factor_mesh,
    make_mesh,
    mesh_from_sizes,
)

__all__ = [
    "enable_compilation_cache",
    "initialize",
    "is_simulated",
    "process_info",
    "simulate_cpu_devices",
    "AXIS_ORDER",
    "DATA_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "SEQ_AXIS",
    "MeshConfig",
    "factor_mesh",
    "make_mesh",
    "mesh_from_sizes",
]
