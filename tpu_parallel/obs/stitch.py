"""Cross-process trace stitching: N span logs -> ONE Perfetto timeline.

Every fleet process records spans on its OWN monotonic clock — the
readings are not comparable across hosts (each process's zero is its
own boot).  What IS comparable: at every wire crossing the router holds
a send/recv timestamp pair around the peer's reported clock reading
(the ``clock_sync`` instants its probe pump and submit path drop, attrs
``peer`` / ``t_send`` / ``t_recv`` / ``peer_ts``).  For a peer whose
one-way delays are roughly symmetric,

    offset = (t_send + t_recv) / 2 - peer_ts

rebases that peer's clock onto the router's, with error bounded by the
sample's RTT — so :func:`clock_offsets` keeps the minimum-RTT sample
per peer (NTP's discipline), and skew just rides into the offset.

:func:`stitch_traces` takes the processes' span-log records (the
``/v1/tracez`` payloads, or :func:`tpu_parallel.obs.spool.read_span_log`
output) and emits one Chrome trace-event JSON: one pid per process, one
tid per track, spans as ``X``/``b``/``e`` events, instants as ``i`` —
plus FLOW ARROWS (``s``/``f`` pairs) from each wire-crossing span to
the first span its receiver emitted for the same trace, found through
the span identity chain (the receiver's spans parent to the forked
context id the sender assigned to its wire span; see
:class:`tpu_parallel.obs.tracer.TraceContext`).

:func:`trace_summary` judges the stitched forest (span counts, pids
touched, single-rootedness), and :func:`phase_breakdown` attributes one
request's latency to phases (queue / prefill / decode / KV wire / SSE
relay) — durations are offset-invariant, so attribution needs no clock
alignment at all.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "clock_offsets",
    "stitch_traces",
    "trace_summary",
    "phase_breakdown",
]

# phase vocabulary: span name (prefix) -> fleet_phase_seconds label
PHASE_OF_SPAN = (
    ("queue", "queue"),
    ("prefill", "prefill"),
    ("decode", "decode"),
    ("wire:kv", "kv_wire"),
    ("wire:", "wire"),
    ("relay", "relay"),
)

_WIRE_PREFIX = "wire:"


def clock_offsets(records: Sequence[Dict]) -> Dict[str, Dict]:
    """Per-peer clock offset from the root process's ``clock_sync``
    instants, minimum-RTT sample wins.  Returns
    ``{peer_addr: {"offset": s, "rtt": s, "samples": n}}`` where
    ``root_time ~= peer_time + offset``."""
    best: Dict[str, Dict] = {}
    for rec in records:
        if rec.get("kind") != "instant" or rec.get("name") != "clock_sync":
            continue
        attrs = rec.get("attrs") or {}
        peer = attrs.get("peer")
        try:
            t_send = float(attrs["t_send"])
            t_recv = float(attrs["t_recv"])
            peer_ts = float(attrs["peer_ts"])
        except (KeyError, TypeError, ValueError):
            continue
        rtt = t_recv - t_send
        if peer is None or rtt < 0:
            continue
        offset = (t_send + t_recv) / 2.0 - peer_ts
        cur = best.get(peer)
        if cur is None:
            best[peer] = {"offset": offset, "rtt": rtt, "samples": 1}
        else:
            cur["samples"] += 1
            if rtt < cur["rtt"]:
                cur["offset"], cur["rtt"] = offset, rtt
    return best


def _spans_of(proc: Dict) -> List[Dict]:
    return [r for r in proc.get("records", ())
            if r.get("kind") == "span" and r.get("end") is not None]


def _instants_of(proc: Dict) -> List[Dict]:
    return [r for r in proc.get("records", ())
            if r.get("kind") == "instant"]


def _root_index(processes: Sequence[Dict]) -> int:
    """The root process: the one holding clock_sync samples (the
    router), else the first."""
    for i, proc in enumerate(processes):
        for rec in proc.get("records", ()):
            if rec.get("kind") == "instant" \
                    and rec.get("name") == "clock_sync":
                return i
    return 0


def _process_offsets(processes: Sequence[Dict]) -> List[float]:
    """One rebasing offset per process, onto the root's clock.  A
    process without a clock_sync sample (its ``addr`` never probed in
    the captured window) falls back to aligning its earliest record
    with the root's — coarse, but it keeps the timeline renderable and
    is exact for same-host fake clocks started together."""
    root = _root_index(processes)
    offsets_by_addr = clock_offsets(processes[root].get("records", ()))
    root_starts = [r.get("start", r.get("ts"))
                   for r in processes[root].get("records", ())
                   if r.get("kind") in ("span", "instant")]
    root_min = min((t for t in root_starts if t is not None), default=0.0)
    out: List[float] = []
    for i, proc in enumerate(processes):
        if i == root:
            out.append(0.0)
            continue
        sample = offsets_by_addr.get(proc.get("addr"))
        if sample is not None:
            out.append(sample["offset"])
            continue
        starts = [r.get("start", r.get("ts"))
                  for r in proc.get("records", ())
                  if r.get("kind") in ("span", "instant")]
        local_min = min((t for t in starts if t is not None), default=0.0)
        out.append(root_min - local_min)
    return out


def _span_args(rec: Dict) -> Dict:
    args = dict(rec.get("attrs") or {})
    for key in ("trace_id", "span_id", "parent_id"):
        if rec.get(key) is not None:
            args[key] = rec[key]
    return args


def stitch_traces(processes: Sequence[Dict]) -> Dict:
    """Emit ONE Chrome trace over every process's records.

    ``processes``: sequence of ``{"name", "pid", "records"}`` dicts
    (``addr`` required on non-root processes for exact clock alignment;
    ``skipped`` passed through into the summary).  Returns
    ``{"traceEvents": [...], "metadata": {...}}``.
    """
    processes = list(processes)
    if not processes:
        return {"traceEvents": [], "metadata": {"processes": []}}
    offsets = _process_offsets(processes)
    events: List[Dict] = []
    # spans indexed by identity, for the flow pass
    span_site: Dict[str, Tuple[int, Dict]] = {}  # span_id -> (proc_i, rec)
    by_trace: Dict[str, Dict[int, List[Dict]]] = {}

    for i, proc in enumerate(processes):
        pid = int(proc.get("pid", i + 1))
        offset = offsets[i]
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": proc.get("name", f"proc{i}")},
        })
        tids: Dict[str, int] = {}

        def tid_of(track: str, pid=pid, tids=tids) -> int:
            if track not in tids:
                tids[track] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tids[track], "args": {"name": track},
                })
                events.append({
                    "ph": "M", "name": "thread_sort_index", "pid": pid,
                    "tid": tids[track],
                    "args": {"sort_index": tids[track]},
                })
            return tids[track]

        for rec in _spans_of(proc):
            ts = (rec["start"] + offset) * 1e6
            tid = tid_of(rec.get("track", "main"))
            sid = rec.get("span_id")
            if sid:
                span_site[sid] = (i, rec)
            trace_id = rec.get("trace_id")
            if trace_id:
                by_trace.setdefault(trace_id, {}).setdefault(
                    i, []
                ).append(rec)
            if rec.get("async_id") is not None:
                shared = {
                    "cat": "async", "name": rec.get("name", "?"),
                    "id": str(rec["async_id"]), "pid": pid, "tid": tid,
                }
                events.append(dict(shared, ph="b", ts=ts,
                                   args=_span_args(rec)))
                events.append(dict(
                    shared, ph="e",
                    ts=(rec["end"] + offset) * 1e6,
                ))
            else:
                events.append({
                    "ph": "X", "name": rec.get("name", "?"),
                    "cat": rec.get("track", "main"), "pid": pid,
                    "tid": tid, "ts": ts,
                    "dur": max(0.0, (rec["end"] - rec["start"]) * 1e6),
                    "args": _span_args(rec),
                })
        for rec in _instants_of(proc):
            events.append({
                "ph": "i", "name": rec.get("name", "?"),
                "pid": pid, "tid": tid_of(rec.get("track", "main")),
                "ts": (rec.get("ts", 0.0) + offset) * 1e6, "s": "t",
                "args": _span_args(rec),
            })

    # flow arrows: receiver's first span -> the sender's wire span it
    # parents to (the forked-context splice)
    flows = 0
    for trace_id, procs in sorted(by_trace.items()):
        for i, recs in sorted(procs.items()):
            first = min(recs, key=lambda r: r["start"])
            parent = first.get("parent_id")
            site = span_site.get(parent) if parent else None
            if site is None or site[0] == i:
                continue
            src_i, src = site
            flows += 1
            flow_id = f"{trace_id}:{flows}"
            events.append({
                "ph": "s", "cat": "trace", "name": "handoff",
                "id": flow_id,
                "pid": int(processes[src_i].get("pid", src_i + 1)),
                "tid": _tid_lookup(events, processes, src_i, src),
                "ts": (src["start"] + offsets[src_i]) * 1e6,
            })
            events.append({
                "ph": "f", "cat": "trace", "name": "handoff",
                "bp": "e", "id": flow_id,
                "pid": int(processes[i].get("pid", i + 1)),
                "tid": _tid_lookup(events, processes, i, first),
                "ts": (first["start"] + offsets[i]) * 1e6,
            })
    return {
        "traceEvents": events,
        "metadata": {
            "processes": [
                {"name": p.get("name"), "pid": p.get("pid"),
                 "addr": p.get("addr"),
                 "clock_offset_seconds": offsets[i],
                 "skipped": p.get("skipped")}
                for i, p in enumerate(processes)
            ],
            "flow_arrows": flows,
        },
    }


def _tid_lookup(events: Sequence[Dict], processes: Sequence[Dict],
                proc_i: int, rec: Dict) -> int:
    """The tid already assigned to ``rec``'s track in ``proc_i`` (the
    metadata events are emitted before any flow pass runs)."""
    pid = int(processes[proc_i].get("pid", proc_i + 1))
    track = rec.get("track", "main")
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name" \
                and ev.get("pid") == pid \
                and ev.get("args", {}).get("name") == track:
            return ev["tid"]
    return 0


def trace_summary(processes: Sequence[Dict]) -> Dict[str, Dict]:
    """Judge the stitched forest: for every trace id, the span count,
    the pids it touched, whether its span tree is SINGLE-ROOTED (one
    span without an in-trace parent — the router's root span; a second
    root means a context was dropped at some crossing), and whether a
    cross-process parent link (a flow arrow) exists."""
    spans_by_trace: Dict[str, List[Tuple[int, Dict]]] = {}
    for i, proc in enumerate(processes):
        for rec in _spans_of(proc):
            tid = rec.get("trace_id")
            if tid:
                spans_by_trace.setdefault(tid, []).append((i, rec))
    out: Dict[str, Dict] = {}
    for trace_id, sited in sorted(spans_by_trace.items()):
        ids = {r.get("span_id") for _i, r in sited if r.get("span_id")}
        roots = [r for _i, r in sited
                 if not r.get("parent_id") or r["parent_id"] not in ids]
        site_of = {r.get("span_id"): i for i, r in sited
                   if r.get("span_id")}
        cross_links = sum(
            1 for i, r in sited
            if r.get("parent_id") in site_of
            and site_of[r["parent_id"]] != i
        )
        pids = sorted({
            int(processes[i].get("pid", i + 1)) for i, _r in sited
        })
        out[trace_id] = {
            "spans": len(sited),
            "pids": pids,
            "roots": len(roots),
            "single_rooted": len(roots) == 1,
            "cross_process_links": cross_links,
        }
    return out


def phase_breakdown(records: Sequence[Dict]) -> Dict:
    """Attribute one trace's records to latency phases.  ``records``
    is every span/instant of ONE trace across all processes (clock
    alignment unnecessary: durations are offset-invariant).  Returns
    ``{"phases": {phase: {"seconds", "count"}}, "kv_wire_bytes": n,
    "spans": n}``."""
    phases: Dict[str, Dict[str, float]] = {}
    kv_bytes = 0.0
    spans = 0
    for rec in records:
        if rec.get("kind") != "span" or rec.get("end") is None:
            continue
        spans += 1
        name = rec.get("name", "")
        phase = None
        for prefix, label in PHASE_OF_SPAN:
            if name.startswith(prefix):
                phase = label
                break
        if phase is None:
            continue
        slot = phases.setdefault(phase, {"seconds": 0.0, "count": 0})
        slot["seconds"] += max(0.0, rec["end"] - rec["start"])
        slot["count"] += 1
        if phase == "kv_wire":
            try:
                kv_bytes += float(
                    (rec.get("attrs") or {}).get("bytes", 0) or 0
                )
            except (TypeError, ValueError):
                pass
    return {
        "phases": {
            k: {"seconds": round(v["seconds"], 6), "count": v["count"]}
            for k, v in sorted(phases.items())
        },
        "kv_wire_bytes": kv_bytes,
        "spans": spans,
    }
