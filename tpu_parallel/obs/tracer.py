"""Request-lifecycle span tracer.

Records WHAT happened WHEN as named spans on named tracks: the serving
engine opens one track per cache slot plus a ``scheduler`` track, the
trainer a ``trainer`` track, and :mod:`tpu_parallel.obs.exporters` lays
the spans out as a Chrome trace-event file Perfetto opens directly — one
request's life reads left to right as
``queue -> prefill[chunk i] -> decode/verify... -> finish``.

Two span shapes:

- **Complete spans** (the default): a ``[start, end]`` interval on one
  track.  Spans on a track must be sequential or properly nested (the
  Chrome ``X`` event contract); everything the engine emits per tick is.
- **Async spans** (``start_async``): intervals that legitimately overlap
  others on their track — queue-wait spans of concurrently queued
  requests.  Exported as Chrome ``b``/``e`` nestable-async pairs, which
  Perfetto renders on per-id sub-rows instead of corrupting the track.

Timestamps come from an injectable monotonic ``clock`` so lifecycle tests
run on a fake clock, deterministically.

**Disabled tracing is near-zero cost**: the module-level :data:`NULL_TRACER`
(the engine/trainer default) returns one shared no-op span from every
call — no timestamp read, no allocation, no list append.  Hot loops that
would even BUILD attribute dicts per token guard on ``tracer.enabled``.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional


class Span:
    """One named interval on a track.  Usable as a context manager for
    lexically-scoped work, or held across ticks and closed with
    :meth:`finish` (the engine's queue-wait spans live for many ticks)."""

    __slots__ = ("name", "track", "start", "end", "attrs", "async_id",
                 "_tracer")

    def __init__(self, tracer: "Tracer", name: str, track: str,
                 attrs: Dict[str, object], start: float,
                 async_id: Optional[str] = None):
        self._tracer = tracer
        self.name = name
        self.track = track
        self.attrs = attrs
        self.start = start
        self.end: Optional[float] = None
        self.async_id = async_id

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def finish(self, **attrs) -> "Span":
        if attrs:
            self.attrs.update(attrs)
        if self.end is None:
            self.end = self._tracer.now()
        return self

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class _NullSpan:
    """The shared do-nothing span: every NullTracer call returns THIS
    object, so a disabled tracer allocates nothing per call."""

    __slots__ = ()

    def set(self, **attrs) -> "_NullSpan":
        return self

    def finish(self, **attrs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Append-only span/instant recorder.

    ``span``/``start`` open a complete span (``span`` reads better under
    ``with``; they are the same call), ``start_async`` an overlap-safe
    async span, ``record`` retro-records an interval measured by the
    caller (the engine's batched prefill fans one device call out into
    per-slot spans sharing the measured window), ``instant`` drops a
    zero-duration marker.
    """

    enabled = True

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.spans: List[Span] = []
        self.instants: List[Dict] = []

    def now(self) -> float:
        return self.clock()

    def start(self, name: str, track: str = "main", **attrs) -> Span:
        span = Span(self, name, track, attrs, self.clock())
        self.spans.append(span)
        return span

    span = start

    def start_async(self, name: str, track: str, async_id: str,
                    **attrs) -> Span:
        span = Span(self, name, track, attrs, self.clock(),
                    async_id=async_id)
        self.spans.append(span)
        return span

    def record(self, name: str, track: str, start: float, end: float,
               **attrs) -> Span:
        span = Span(self, name, track, attrs, start)
        span.end = end
        self.spans.append(span)
        return span

    def instant(self, name: str, track: str = "main", **attrs) -> None:
        self.instants.append(
            {"name": name, "track": track, "ts": self.clock(),
             "attrs": attrs}
        )

    def tracks(self) -> List[str]:
        """Every track touched, ``scheduler`` and ``trainer`` first, the
        rest natural-sorted (``slot 2`` before ``slot 10``) — the
        exporter's row order."""
        seen = {s.track for s in self.spans}
        seen.update(ev["track"] for ev in self.instants)
        head = [t for t in ("scheduler", "trainer") if t in seen]

        def natural(track: str):
            prefix, _, tail = track.rpartition(" ")
            if tail.isdigit():
                return (prefix, int(tail))
            return (track, -1)

        return head + sorted(seen - set(head), key=natural)


class NullTracer:
    """The disabled tracer: same surface as :class:`Tracer`, no clock
    reads, no storage.  ``enabled`` is False so hot loops can skip even
    building the attribute dicts."""

    enabled = False

    def now(self) -> float:
        return 0.0

    def start(self, name: str, track: str = "main", **attrs) -> _NullSpan:
        return NULL_SPAN

    span = start

    def start_async(self, name: str, track: str, async_id: str,
                    **attrs) -> _NullSpan:
        return NULL_SPAN

    def record(self, name: str, track: str, start: float, end: float,
               **attrs) -> _NullSpan:
        return NULL_SPAN

    def instant(self, name: str, track: str = "main", **attrs) -> None:
        pass

    def tracks(self) -> List[str]:
        return []

    @property
    def spans(self) -> List[Span]:
        return []

    @property
    def instants(self) -> List[Dict]:
        return []


NULL_TRACER = NullTracer()
