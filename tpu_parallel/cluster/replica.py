"""One serving replica as the cluster sees it: an engine plus health.

A production cluster never talks to a :class:`~tpu_parallel.serving.engine.
ServingEngine` directly — it talks to a :class:`ReplicaHandle`, which adds
the three things scale-out needs on top of the engine's tick loop:

- **Health state** (``healthy`` / ``degraded`` / ``dead``): routers skip
  dead replicas outright and deprioritize degraded (stalled) ones; the
  frontend retries a dead replica's in-flight work elsewhere.  ANY
  exception escaping ``engine.step()`` marks the replica dead — a replica
  that throws mid-tick has an engine in an unknown state, and the only
  safe move is to stop routing to it and replay its work.
- **Load accounting**: queue depth + active slots + estimated pending
  prefill tokens, combined into one comparable ``load()`` scalar (the
  least-loaded router's sort key).  Everything is host-side bookkeeping
  the engine already tracks — reading load never touches the device.
- **Fault injection** (:class:`FaultPlan`): deterministic crash / stall /
  admission-reject faults keyed on the replica's own tick count, so
  failover tests replay EXACTLY (crash on tick 7 is crash on tick 7,
  every run).  A ``FaultPlan`` is how the acceptance suite proves the
  bitwise-exactness-under-failure story without flaky process killing.

The handle also keeps the replica-local request ledger (every submitted,
not-yet-terminal engine :class:`RequestOutput`): when the replica dies,
``orphans()`` is precisely the work the frontend must re-route.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from tpu_parallel.serving.engine import ServingEngine
from tpu_parallel.serving.request import Request, RequestOutput

# replica health states
HEALTHY = "healthy"  # routable
DEGRADED = "degraded"  # stalled/slow: routable only when nothing healthy is
DEAD = "dead"  # never routable; in-flight work must be replayed elsewhere

# ``load()`` weight of one pending prefill token relative to one queued
# request / one active slot: a slot decodes one token per tick while a
# queued prompt costs its whole length in prefill work, so tokens are
# discounted to rough slot-tick equivalents (64 prompt tokens ~ one
# request's worth of near-term work).  The constant only needs to rank
# replicas consistently, not model latency.
PREFILL_TOKEN_WEIGHT = 1.0 / 64.0


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Deterministic fault schedule keyed on the replica's OWN tick count
    (the number of ``step()`` calls it has served).

    - ``crash_at_tick``: the step with this index raises
      :class:`ReplicaDead` instead of running — the engine is abandoned
      mid-flight exactly as a process kill would leave it.
    - ``stall_at_tick`` + ``stall_ticks``: steps in
      ``[stall_at_tick, stall_at_tick + stall_ticks)`` do nothing (no
      engine tick) and the replica reports DEGRADED — the GC-pause /
      preemption shape.
    - ``reject_at_tick`` + ``reject_ticks``: during that tick window the
      replica refuses NEW admissions (``accepting`` is False) while
      in-flight work proceeds — the overload-shedding shape.
    """

    crash_at_tick: Optional[int] = None
    stall_at_tick: Optional[int] = None
    stall_ticks: int = 0
    reject_at_tick: Optional[int] = None
    reject_ticks: int = 0

    def stalled(self, tick: int) -> bool:
        return (
            self.stall_at_tick is not None
            and self.stall_at_tick <= tick < self.stall_at_tick + self.stall_ticks
        )

    def rejecting(self, tick: int) -> bool:
        return (
            self.reject_at_tick is not None
            and self.reject_at_tick
            <= tick
            < self.reject_at_tick + self.reject_ticks
        )


class ReplicaDead(RuntimeError):
    """Raised by ``ReplicaHandle.step()`` when the replica dies — by
    FaultPlan schedule or by a real exception escaping the engine tick.
    The frontend catches it, collects ``orphans()``, and re-routes."""

    def __init__(self, replica_id: int, cause: Optional[str] = None):
        super().__init__(
            f"replica {replica_id} died"
            + (f" ({cause})" if cause else "")
        )
        self.replica_id = replica_id


class ReplicaHandle:
    """Cluster-side wrapper of one :class:`ServingEngine`.

    ``submit()``/``step()`` mirror the engine surface but maintain the
    health state, the tick counter the :class:`FaultPlan` keys off, and
    the not-yet-terminal request ledger that ``orphans()`` reports after
    a death.  The handle never constructs engines — the caller owns model
    and params placement (same process here; the design point is that
    nothing in the cluster layer assumes it).
    """

    def __init__(
        self,
        replica_id: int,
        engine: ServingEngine,
        fault_plan: Optional[FaultPlan] = None,
    ):
        self.replica_id = replica_id
        self.engine = engine
        self.fault_plan = fault_plan
        self.health = HEALTHY
        self.ticks = 0
        # engine request_id -> live engine RequestOutput; pruned as
        # requests reach a terminal state
        self._ledger: Dict[str, RequestOutput] = {}

    # -- load signals ------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self.engine.scheduler.depth

    @property
    def active_slots(self) -> int:
        return self.engine.in_flight

    @property
    def pending_prefill_tokens(self) -> int:
        return self.engine.pending_prefill_tokens

    def load(self) -> float:
        """One comparable scalar: queued requests + occupied slots +
        discounted pending prefill tokens (see ``PREFILL_TOKEN_WEIGHT``).
        A dead replica reports infinite load so any ranking consumer that
        forgets to filter by health still never picks it."""
        if self.health == DEAD:
            return float("inf")
        return (
            self.queue_depth
            + self.active_slots
            + self.pending_prefill_tokens * PREFILL_TOKEN_WEIGHT
        )

    @property
    def routable(self) -> bool:
        """Placeable for frontend dispatch: alive and not inside a
        FaultPlan admission-reject window.  Deliberately IGNORES the
        engine's drain gate — frontend dispatch relocates already-
        accepted work (``requeue=True``), which the gate waves through;
        a draining cluster must still be able to land its re-routed
        queue remainders."""
        if self.health == DEAD:
            return False
        if self.fault_plan is not None and self.fault_plan.rejecting(
            self.ticks
        ):
            return False
        return True

    @property
    def accepting(self) -> bool:
        """Accepting NEW admissions: routable AND not drain-gated."""
        return self.routable and not self.engine.draining

    # -- work --------------------------------------------------------------

    def submit(
        self,
        request: Request,
        requeue: bool = False,
        arrival_time: Optional[float] = None,
    ) -> RequestOutput:
        """Hand one request to the replica's engine; tracks it in the
        ledger unless the engine rejected it synchronously."""
        if self.health == DEAD:
            raise ReplicaDead(self.replica_id, "submit to dead replica")
        out = self.engine.add_request(
            request, requeue=requeue, arrival_time=arrival_time
        )
        if not out.done:
            self._ledger[request.request_id] = out
        return out

    def step(self) -> list:
        """One engine tick under the fault plan.  Raises
        :class:`ReplicaDead` on a scheduled crash or any engine exception
        (health flips to DEAD first, so the raiser's view and a later
        reader's view agree); returns the tick's StreamEvents, or [] for
        a stalled (DEGRADED) tick."""
        if self.health == DEAD:
            raise ReplicaDead(self.replica_id, "step on dead replica")
        tick = self.ticks
        self.ticks += 1
        fp = self.fault_plan
        if fp is not None:
            if fp.crash_at_tick is not None and tick >= fp.crash_at_tick:
                self.health = DEAD
                raise ReplicaDead(self.replica_id, f"fault plan, tick {tick}")
            if fp.stalled(tick):
                self.health = DEGRADED
                return []
        if self.health == DEGRADED:
            self.health = HEALTHY  # stall window over
        try:
            events = self.engine.step()
        except Exception as exc:  # engine state unknown: replica is gone
            self.health = DEAD
            raise ReplicaDead(self.replica_id, repr(exc)) from exc
        self._prune()
        return events

    def has_work(self) -> bool:
        return self.health != DEAD and self.engine.has_work()

    def _prune(self) -> None:
        done = [rid for rid, out in self._ledger.items() if out.done]
        for rid in done:
            del self._ledger[rid]

    def orphans(self) -> List[RequestOutput]:
        """Every tracked request that had NOT reached a terminal state —
        queued or holding a slot — in submission order.  After a death
        this is exactly the work the frontend replays elsewhere (tokens
        already delivered ride along on each RequestOutput, so the replay
        can force-prefix them)."""
        self._prune()
        return list(self._ledger.values())

    def forget(self, request_id: str) -> None:
        """Drop one request from the ledger (the frontend pulled it back
        for re-routing — e.g. a drain's queued remainder)."""
        self._ledger.pop(request_id, None)

    def take_queued(self) -> List[RequestOutput]:
        """Pull the engine's queued remainder (FIFO order) out of this
        replica for re-routing, dropping each from the ledger."""
        taken = self.engine.scheduler.take_queued()
        for out in taken:
            self.forget(out.request.request_id)
        return taken

    def summary(self) -> dict:
        return {
            "replica": self.replica_id,
            "health": self.health,
            "ticks": self.ticks,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "pending_prefill_tokens": self.pending_prefill_tokens,
            "load": None if self.health == DEAD else round(self.load(), 3),
        }
