"""LRU prefix cache: skip recomputing shared prompt prefixes entirely.

Production prompt streams are heavily prefix-shared — system prompts,
few-shot headers, templated instructions — and a continuous-batching
engine re-prefills those identical tokens for every request.  Cached K/V
is a pure function of (token ids, positions, params), including the int8
path's per-(position, kv-head) quantization, so a prefix computed once
can be COPIED into a fresh slot (:meth:`CachePool.copy_prefix`) with
bit-identical results; only the prompt remainder runs the model.

Keys are BUCKET-ALIGNED token prefixes (the engine's prefill buckets), so
lookups are O(#buckets) exact-match probes instead of a longest-common-
prefix search: for a prompt of length L the engine probes the largest
bucket B <= L-1 downward and takes the first hit.  (L-1, not L: a full-
prompt hit would leave no remainder token, and the FIRST sampled token
needs the last real token's hidden state — cached K/V alone cannot
produce logits.)

Entries are whole pool rows (seq_len-long K/V per layer) — real HBM — so
the cache is small and LRU-evicted; ``max_entries`` bounds it.  Hit/miss/
eviction counters feed :class:`~tpu_parallel.serving.metrics.ServingMetrics`.

Under the BLOCK-PAGED pool the store is a different economy: entries hold
refcounted physical block-id tuples instead of copied rows (a hit is a
table pointer write + refcount bump — O(1), zero K/V copies), and the
``on_evict`` callback lets the engine return an evicted entry's block
references to the :class:`~tpu_parallel.serving.cache_pool.BlockAllocator`.
The LRU/lookup machinery is identical either way — the cache never
inspects its values.

This is the ALIGNED-LRU tier-0 cache.  The paged path can swap it for
the token-level radix hierarchy
(:class:`~tpu_parallel.serving.kv_hierarchy.RadixPrefixCache` —
block-granular matching, frequency-aware eviction, host-RAM offload
tier) via ``ServingEngine(kv_radix_cache=True)``; both expose the same
lookup/store/evict/counter surface to the engine and metrics.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Sequence, Tuple


class PrefixCache:
    """Exact-match LRU over bucket-aligned token prefixes.

    Keys are token-id tuples (dict hashing gives the "hash-keyed" lookup
    with zero collision risk); values are ``(row_tree, length)`` where
    ``row_tree`` is a batch-1 cache row whose first ``length`` positions
    hold the prefix (the engine trims validity at copy time, so rows are
    stored as extracted — no rewrite on the store path).
    """

    def __init__(self, max_entries: int = 8, on_evict=None):
        if max_entries < 1:
            raise ValueError(f"max_entries={max_entries} < 1")
        self.max_entries = max_entries
        self._entries: "OrderedDict[Tuple[int, ...], tuple]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # called with each LRU-evicted (row_tree, length) entry — the
        # paged pool's refcount-release hook (None = entries just drop)
        self.on_evict = on_evict

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return tuple(key) in self._entries

    def reset_counters(self) -> None:
        """Zero the hit/miss/eviction tallies (entries stay) — benches
        call this after a warm-up phase so measured-window rates are not
        polluted by warm traffic."""
        self.hits = self.misses = self.evictions = 0

    def lookup(self, prompt: Sequence[int], buckets: Sequence[int]):
        """Longest bucket-aligned cached prefix of ``prompt`` STRICTLY
        shorter than the prompt; returns ``(row_tree, length)`` or None.
        One counted hit or miss per call (per admission, not per probe).
        """
        prompt = tuple(int(t) for t in prompt)
        for b in sorted(buckets, reverse=True):
            if b >= len(prompt):
                continue
            entry = self._entries.get(prompt[:b])
            if entry is not None:
                self._entries.move_to_end(prompt[:b])
                self.hits += 1
                return entry
        self.misses += 1
        return None

    def store(self, prompt: Sequence[int], buckets: Sequence[int],
              row_tree) -> list:
        """Store ``row_tree`` (a freshly prefilled slot row for ``prompt``)
        under EVERY bucket-aligned proper-prefix key not already cached —
        a long prompt seeds its short shared header (the system-prompt
        case) and its long few-shot prefix in one pass, all referencing
        the SAME immutable row (copy_prefix trims validity to each key's
        length at hit time, so one stored row serves every aligned
        sub-prefix).  First writer wins per key.  Returns the newly stored
        prefix lengths."""
        prompt = tuple(int(t) for t in prompt)
        stored = []
        for b in sorted(buckets, reverse=True):
            if b >= len(prompt):
                continue
            key = prompt[:b]
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            self._entries[key] = (row_tree, b)
            stored.append(b)
        self._evict_overflow()
        return stored

    def store_one(self, prefix, length: int, row_tree) -> bool:
        """Store ONE entry under the exact ``prefix`` key (first writer
        wins; a refused store returns False so the caller can release
        whatever references ``row_tree`` carries).  The paged pool's store
        path — each bucket-aligned key holds its OWN refcounted block
        tuple, so eviction accounting stays per-key."""
        key = tuple(int(t) for t in prefix)
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        self._entries[key] = (row_tree, int(length))
        self._evict_overflow()
        return True

    def values(self):
        """Snapshot of the stored entry values (``(payload, length)``
        pairs, LRU order) — the metrics mirror's entry-bytes accounting
        reads block counts off these without reaching into the dict."""
        return list(self._entries.values())

    def pop_lru(self) -> bool:
        """Evict the least-recently-used entry NOW; False when empty.
        The paged engine's block-pressure valve: stored entries hold
        refcounted blocks indefinitely, so when the admission gate cannot
        seat the queue head it trades cold cached prefixes for capacity
        instead of starving the head forever."""
        if not self._entries:
            return False
        _, entry = self._entries.popitem(last=False)
        self.evictions += 1
        if self.on_evict is not None:
            self.on_evict(entry)
        return True

    def _evict_overflow(self) -> None:
        while len(self._entries) > self.max_entries:
            self.pop_lru()
