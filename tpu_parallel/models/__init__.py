from tpu_parallel.models.gpt import (
    GPTConfig,
    GPTLM,
    gpt2_125m,
    gpt2_350m,
    EncoderClassifier,
    bert_base,
    bert_base_hf,
    llama_1b,
    make_gpt_loss,
    make_mlm_loss,
    tiny_test,
)
from tpu_parallel.models.layers import TransformerConfig
from tpu_parallel.models.mlp import MLPClassifier, MLPConfig
from tpu_parallel.models.seq2seq import (
    EncoderDecoder,
    Seq2SeqBatch,
    Seq2SeqConfig,
    make_seq2seq_loss,
    seq2seq_generate,
    t5_small,
    tiny_seq2seq,
)
from tpu_parallel.models.hf import from_hf_bert, from_hf_gpt2, from_hf_llama, to_hf_gpt2
from tpu_parallel.models.quantize import (
    QuantizedTensor,
    dequantize_params,
    quantize_params,
    quantized_nbytes,
)

__all__ = [
    "from_hf_gpt2",
    "from_hf_llama",
    "to_hf_gpt2",
    "QuantizedTensor",
    "dequantize_params",
    "quantize_params",
    "quantized_nbytes",
    "GPTConfig",
    "GPTLM",
    "gpt2_125m",
    "gpt2_350m",
    "EncoderClassifier",
    "bert_base",
    "bert_base_hf",
    "from_hf_bert",
    "llama_1b",
    "make_gpt_loss",
    "make_mlm_loss",
    "tiny_test",
    "TransformerConfig",
    "MLPClassifier",
    "MLPConfig",
    "EncoderDecoder",
    "Seq2SeqBatch",
    "Seq2SeqConfig",
    "make_seq2seq_loss",
    "seq2seq_generate",
    "t5_small",
    "tiny_seq2seq",
]
