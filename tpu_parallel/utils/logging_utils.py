"""Metric logging: stdout + JSONL file sink.

Replaces the reference's ``print_metrics``-only observability
(``util.py:170-181``) with a logger that keeps machine-readable history
(one JSON object per log step) next to the human-readable stream — and only
on process 0 of a multi-host run.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

import jax


def print_exception(exc: BaseException, *, width: int = 100) -> str:
    """Compact, colored one-glance rendering of an exception.

    Capability parity with the reference's ``print_exception``
    (``util.py:12-14``: red exception type via termcolor + textwrap) — here
    with plain ANSI codes (no termcolor dependency), the wrapped message
    included, and TTY detection so piped logs stay clean.  Returns the
    rendered string (also printed); ``Trainer.fit`` calls this on step
    failures before deciding whether to roll back.
    """
    import sys
    import textwrap

    name = type(exc).__name__
    use_color = hasattr(sys.stderr, "isatty") and sys.stderr.isatty()
    title = f"\033[91m{name}\033[0m" if use_color else name
    body = textwrap.fill(str(exc), width=width) or "(no message)"
    rendered = f"{title}\n{body}"
    print(rendered, file=sys.stderr, flush=True)
    return rendered


def _to_scalar(value):
    """Coerce a metric value to a JSON-serializable Python scalar.

    Trainer/engine metrics routinely arrive as 0-d jax/numpy arrays (a
    ``loss`` straight off the device); ``json.dumps`` rejects those and
    used to crash the sink mid-run.  ``item()`` unwraps any 0-d array
    (host transfer for a jax scalar); everything else passes through for
    ``json.dumps`` to judge."""
    if isinstance(value, (int, float, str, bool)) or value is None:
        return value
    item = getattr(value, "item", None)
    if item is not None and getattr(value, "ndim", 0) == 0:
        value = item()
        # np.item() yields Python scalars; keep only JSON-native results
        if isinstance(value, (int, float, str, bool)):
            return value
    return value


class MetricLogger:
    def __init__(self, logdir: Optional[str] = None, name: str = "train"):
        self.is_main = jax.process_index() == 0
        self.file = None
        if logdir and self.is_main:
            os.makedirs(logdir, exist_ok=True)
            self.path = os.path.join(logdir, f"{name}.jsonl")
            self.file = open(self.path, "a")
        self._t0 = time.time()

    def _emit(self, record: Dict, text: str) -> None:
        """The one sink write path: ``text`` to stdout, ``record`` as a
        JSONL line (both process-0-gated by the callers)."""
        print(text, flush=True)
        if self.file is not None:
            self.file.write(json.dumps(record) + "\n")
            self.file.flush()

    def log(self, step: int, metrics: Dict[str, float]) -> None:
        if not self.is_main:
            return
        metrics = {k: _to_scalar(v) for k, v in metrics.items()}
        record = {"step": step, "time": round(time.time() - self._t0, 3), **metrics}
        parts = " ".join(f"{k}={v:.5g}" for k, v in sorted(metrics.items()))
        self._emit(record, f"[step {step}] {parts}")

    def log_record(self, record: Dict) -> None:
        """Append one arbitrary JSON record to the sink (process 0 only) —
        the one-shot form of :meth:`log` for end-of-run summaries
        (serve_bench perf records, eval reports): no step counter, no
        float formatting, values pass through as-is."""
        if not self.is_main:
            return
        self._emit(record, json.dumps(record))

    def close(self) -> None:
        if self.file is not None:
            self.file.close()
            self.file = None
