"""Chaos-soak harness: seeded randomized fault storms against the
self-healing cluster, with hard fleet invariants.

A production scheduler treats recovery as the COMMON case; this harness
proves it.  From one seed it draws a per-replica fault schedule
(``FaultPlan.from_seed`` — crashes, observed stalls, flapping
crash-loops, admission-reject windows, mixed per replica), drives a
request stream through the :class:`~tpu_parallel.cluster.Frontend` with
the progress watchdog and :class:`~tpu_parallel.cluster.RestartPolicy`
circuit breaker armed, and asserts the invariants the self-healing
story stands on:

1. **Termination** — every accepted request reaches a terminal state
   (nothing pends forever through a full-fleet flap).
2. **Exactness** — every request FINISHES and its greedy token stream is
   bitwise identical to a no-fault single-engine baseline, through every
   crash, watchdog kill, restart and probation hand-off.
3. **No leaks** — zero open token-budget reservations at the end, and
   every live replica's cache pool is fully released with aligned
   position tables.
4. **Healing** — every dead replica with restart budget left actually
   came back, and at least one restarted replica passed probation and
   served completed requests afterward.

Everything runs on a FAKE clock advanced ``--dt`` per cluster tick, so
the whole storm — including the breaker's exponential backoff — is a
deterministic function of the seed: same seed, same storm, same
recovery, every run (the tier-1 smoke in ``tests/test_cluster.py``
pins one).

Usage:
  python scripts/chaos_bench.py [--seed S] [--replicas N] [--requests N]
      [--slots K] [--new T] [--router rr|least|prefix] [--horizon H]
      [--max-ticks M] [--record CHAOS_r01.json]

Exits nonzero on any invariant violation.  ``--record`` writes one JSON
record (schedule summary, death/restart/watchdog tallies, invariant
verdicts) in the style of the ``SERVE_r*.json`` rounds.
"""

import argparse
import dataclasses
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

REQUIRED_KINDS = ("crash", "stall", "flap")  # the storm must contain each


def make_prompts(cfg, rnd, n_requests, lo, hi):
    return [
        [rnd.randrange(1, cfg.vocab_size)
         for _ in range(rnd.randint(lo, hi))]
        for _ in range(n_requests)
    ]


def baseline_tokens(model, params, prompts, new_tokens, n_slots):
    """Greedy reference: one no-fault engine over the same prompts
    (engine batching is output-invariant, pinned in the serving suite)."""
    from tpu_parallel.serving import Request, SchedulerConfig, ServingEngine

    eng = ServingEngine(
        model, params, n_slots=n_slots,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
    )
    outs = [
        eng.add_request(Request(prompt=p, max_new_tokens=new_tokens))
        for p in prompts
    ]
    eng.run()
    assert all(o.status == "finished" for o in outs)
    return [list(o.tokens) for o in outs]


def build_fault_plans(seed, n_replicas, horizon, swap=False):
    """One seeded :class:`FaultPlan` per replica.  The required kinds
    (crash / stall / flap) spread round-robin across the fleet so every
    storm exercises all three shapes even at 2 replicas; extra reject
    windows land by coin flip.  ``swap`` adds the ``swap@T`` OPERATOR
    event to one seeded replica's plan — the harness (not the plan)
    triggers a fleet-wide rolling weight swap when the cluster reaches
    that tick, so the rollout collides with the storm.  Child rngs
    derive from the master seed, so plans are a pure function of
    (seed, n_replicas, horizon, swap)."""
    from tpu_parallel.cluster import FaultPlan

    master = random.Random(seed)
    kinds = [set() for _ in range(n_replicas)]
    for i, kind in enumerate(REQUIRED_KINDS):
        kinds[i % n_replicas].add(kind)
    for i in range(n_replicas):
        if master.random() < 0.3:
            kinds[i].add("reject")
    if swap:
        kinds[master.randrange(n_replicas)].add("swap")
    plans = []
    for i in range(n_replicas):
        child = random.Random(master.randrange(2 ** 31))
        plans.append(
            FaultPlan.from_seed(child, horizon, kinds=tuple(sorted(kinds[i])))
        )
    return plans


def plan_to_record(plan) -> dict:
    d = dataclasses.asdict(plan)
    factory = d.pop("exception_factory", None)
    d["exception_factory"] = getattr(factory, "__name__", None)
    return {k: v for k, v in d.items() if v not in (None, 0)}


def run_soak(model, params, cfg, prompts, refs, *, seed, n_replicas,
             n_slots, new_tokens, router="least", horizon=64, dt=0.05,
             max_ticks=4000, watchdog_ticks=3, watchdog_kill_ticks=8,
             max_restarts=3, backoff_seconds=0.4, probation_ticks=4,
             probation_requests=2, retry_limit=16, swap=False,
             autopilot=False, autopilot_queue_age_target=None):
    """Drive one seeded storm to completion.  Returns ``(record,
    violations)`` — an empty violations list is a passing soak.

    ``swap=True`` arms the ``swap@T`` operator event: at the seeded
    tick the harness begins a NULL-VALUE rolling weight swap (same
    numbers under a new version id, so the bitwise invariant stays
    meaningful) that must resolve — completed with every live replica
    on the new version, or rolled back with every live replica on the
    old one — without wedging, while replicas crash, stall and flap
    around (and under) it.

    ``autopilot=True`` arms the SLO autopilot in SCALE-ONLY trim
    (``max_shed_fraction=0``: a storm may not lose a single request, so
    shedding is pinned off while scale-up through the probation gate
    collides with the crashes and stalls — and, under ``swap=True``,
    with the mid-storm rollout, where any due scale action must be
    typed-refused rather than interleave).  Every healing invariant
    must hold unchanged; the record carries the controller tallies."""
    from tpu_parallel.cluster import (
        BACKOFF,
        DEAD,
        PROBATION,
        AutopilotPolicy,
        Frontend,
        FrontendConfig,
        ReplicaHandle,
        RestartPolicy,
        SwapPolicy,
    )
    from tpu_parallel.serving import Request, SchedulerConfig, ServingEngine

    t = [0.0]
    clock = lambda: t[0]  # noqa: E731 — the storm's injectable time axis

    def factory():
        # per-step decode tick: fault choreography (stall windows,
        # crash ticks) stays at one-token granularity, matching the
        # failover test suite; jits are shared per model so restarts
        # never recompile
        return ServingEngine(
            model, params, n_slots=n_slots,
            scheduler=SchedulerConfig(max_prefills_per_tick=2),
            clock=clock, decode_steps_per_tick=1,
        )

    plans = build_fault_plans(seed, n_replicas, horizon, swap=swap)
    swap_tick = min(
        (p.swap_at_tick for p in plans if p.swap_at_tick is not None),
        default=None,
    )
    handles = [
        ReplicaHandle(i, factory(), fault_plan=plans[i],
                      engine_factory=factory)
        for i in range(n_replicas)
    ]
    policy = RestartPolicy(
        max_restarts=max_restarts, backoff_seconds=backoff_seconds,
        backoff_factor=2.0, probation_ticks=probation_ticks,
        probation_requests=probation_requests,
    )
    fe = Frontend(
        handles, router=router, clock=clock,
        config=FrontendConfig(
            retry_limit=retry_limit, watchdog_ticks=watchdog_ticks,
            watchdog_kill_ticks=watchdog_kill_ticks, restart=policy,
        ),
    )
    ap = None
    if autopilot:
        ap = fe.enable_autopilot(
            AutopilotPolicy(
                queue_age_target=(
                    autopilot_queue_age_target
                    if autopilot_queue_age_target is not None
                    else 8 * dt
                ),
                window_ticks=4, breach_ticks=2, clear_ticks=8,
                max_shed_fraction=0.0,  # a storm must lose NO request
                max_replicas=n_replicas + 2, min_replicas=n_replicas,
                scale_cooldown_ticks=8,
                # scale-down stays off: retiring a replica before its
                # seeded faults fire would tame the storm under test
                scale_down_idle_ticks=None,
            ),
            factory,
        )

    # arrivals spread over the fault horizon, so traffic keeps flowing
    # while replicas crash, stall and come back — plus an AFTERMATH
    # cohort held until the first restart lands, so a healed replica
    # always has work to prove itself on (a restarted replica with
    # nothing left to serve would prove nothing).  Still deterministic:
    # the release condition is a function of the seeded storm, never of
    # wall time.
    rnd = random.Random(seed + 1)
    n_aftermath = max(2, len(prompts) // 6)
    n_main = len(prompts) - n_aftermath
    arrivals = sorted(
        rnd.randrange(0, max(1, horizon)) for _ in range(n_main)
    )
    outs = []
    ever_died = set()
    # completed requests served by POST-RESTART incarnations, cumulative
    # across incarnations (a healed replica that served and then flapped
    # again still proved the restart path)
    served_after_restart = {h.replica_id: 0 for h in handles}
    tick = 0
    submitted = 0

    swap_begin_state = None

    def tick_once():
        """Advance the fake clock one dt, step the cluster, fold this
        tick's death/served-after-restart observations into the tallies
        the healing invariants are judged on.  The seeded swap@T event
        fires here too — an OPERATOR action colliding with the storm."""
        nonlocal tick, swap_begin_state
        if (
            swap_tick is not None
            and swap_begin_state is None
            and tick >= swap_tick
        ):
            swap_begin_state = fe.begin_swap(
                params=params, version="storm-v2",
                policy=SwapPolicy(
                    drain_ticks=12, canary_ticks=3,
                    canary_seconds=2 * dt, canary_requests=1,
                ),
            )["state"]
        t[0] += dt
        fe.step()
        for h in handles:
            if h.health in (DEAD, BACKOFF):
                ever_died.add(h.replica_id)
            elif h.restarts > 0:
                served_after_restart[h.replica_id] = max(
                    served_after_restart[h.replica_id],
                    h.engine.metrics.finished,
                )
        tick += 1

    while (submitted < len(prompts) or fe.has_work()) and tick < max_ticks:
        while (
            submitted < n_main and arrivals[submitted] <= tick
        ):
            outs.append(
                fe.submit(
                    Request(
                        prompt=prompts[submitted],
                        max_new_tokens=new_tokens,
                    )
                )
            )
            submitted += 1
        if submitted == n_main and (
            any(h.restarts > 0 for h in handles) or tick > 4 * horizon
        ):
            while submitted < len(prompts):
                outs.append(
                    fe.submit(
                        Request(
                            prompt=prompts[submitted],
                            max_new_tokens=new_tokens,
                        )
                    )
                )
                submitted += 1
        tick_once()

    # drive to quiescence: the storm may kill a replica on the very last
    # serving tick; the fleet must be allowed to converge (pending
    # restarts fire, probation resolves, flap budgets burn out, a
    # mid-storm rollout completes or rolls back) before the healing and
    # swap invariants are judged
    while tick < max_ticks and (
        # fe.replicas covers the original fleet AND any autopilot
        # scale-ups still auditioning in probation
        any(h.health in (BACKOFF, PROBATION) for h in fe.replicas)
        or fe.swap_status()["state"] in ("rolling", "rolling_back")
        # a storm that resolves before the seeded swap@T tick still
        # ticks on until the operator event FIRES (an idle-fleet swap
        # is legal; silently skipping it would misreport a refusal)
        or (swap_tick is not None and swap_begin_state is None)
    ):
        tick_once()

    s = fe.summary()
    rec_state = fe.recovery_summary()
    violations = []

    if submitted < len(prompts) or fe.has_work():
        violations.append(
            f"non-termination: {max_ticks} ticks exhausted with "
            f"{sum(1 for o in outs if not o.done)} requests open"
        )
    for i, out in enumerate(outs):
        if not out.done:
            violations.append(f"request {i} not terminal: {out.status}")
        elif out.status != "finished":
            violations.append(
                f"request {i} {out.status} ({out.finish_reason}) — the "
                "storm must lose no request"
            )
        elif list(out.tokens) != list(refs[i]):
            violations.append(
                f"request {i} diverged from the no-fault baseline"
            )
    if s["inflight_tokens"] != 0:
        violations.append(
            f"leaked token-budget reservations: {s['inflight_tokens']}"
        )
    for h in fe.replicas:  # original fleet + autopilot scale-ups
        if h.health in (DEAD, BACKOFF):
            continue  # abandoned engines owe nothing
        pool = h.engine.pool
        if pool.n_free != pool.n_slots:
            violations.append(
                f"replica {h.replica_id} leaked slots: "
                f"{pool.n_free}/{pool.n_slots} free"
            )
        else:
            for slot in range(pool.n_slots):
                pool.assert_slot_aligned(slot)
    if s["replica_deaths"] < 1:
        violations.append("storm produced no deaths — schedule too tame")
    if s["watchdog_degraded"] < 1:
        violations.append(
            "no stall was ever OBSERVED (watchdog never degraded anyone)"
        )
    for h in handles:
        st = rec_state[h.replica_id]
        if h.replica_id in ever_died and st["budget_left"] > 0:
            if h.health in (DEAD, BACKOFF):
                violations.append(
                    f"replica {h.replica_id} dead with "
                    f"{st['budget_left']} restart attempts left"
                )
    healed_and_served = any(
        n > 0 for n in served_after_restart.values()
    )
    if ever_died and s["restarts"] >= 1 and not healed_and_served:
        violations.append(
            "no restarted replica served completed requests afterward"
        )
    if s["restarts"] >= 1 and s["probation_promotions"] < 1:
        violations.append("no restarted replica ever passed probation")
    swap_status = fe.swap_status()
    if swap_tick is not None:
        # the mid-storm rollout must RESOLVE (crashes defer or skip
        # targets, never wedge it) and leave zero version mix among the
        # live fleet
        if swap_begin_state != "rolling":
            violations.append(
                f"swap@{swap_tick} refused: {swap_begin_state}"
            )
        if swap_status["state"] == "completed":
            want = "storm-v2"
        elif swap_status["state"] == "rolled_back":
            want = "initial"
        else:
            want = None
            violations.append(
                f"swap never resolved: {swap_status['state']}"
            )
        if want is not None:
            mixed = {
                h.replica_id: h.weights_version
                for h in handles
                if h.health not in (DEAD, BACKOFF)
                and h.weights_version != want
            }
            if mixed:
                violations.append(
                    f"live replicas off the {want} version after "
                    f"{swap_status['state']}: {mixed}"
                )

    record = {
        "bench": "chaos_soak",
        "model": getattr(cfg, "_name", None) or (
            "gpt2_125m" if jax.default_backend() == "tpu" else "tiny"
        ),
        "backend": jax.default_backend(),
        "seed": seed,
        "replicas": n_replicas,
        "router": router,
        "n_requests": len(prompts),
        "n_slots": n_slots,
        "new_tokens": new_tokens,
        "horizon_ticks": horizon,
        "dt": dt,
        "ticks": tick,
        "fault_plans": [plan_to_record(p) for p in plans],
        "watchdog_ticks": watchdog_ticks,
        "watchdog_kill_ticks": watchdog_kill_ticks,
        "restart_policy": {
            "max_restarts": max_restarts,
            "backoff_seconds": backoff_seconds,
            "probation_ticks": probation_ticks,
            "probation_requests": probation_requests,
        },
        "autopilot": autopilot,
        "autopilot_scale_ups": (
            None if ap is None else s["scale_ups"]
        ),
        "autopilot_refusals": (
            None if ap is None else int(fe.registry.counter(
                "cluster_autopilot_refusals_total",
                reason="swap_in_progress",
            ).value)
        ),
        "autopilot_actions": (
            None if ap is None
            else [
                {"tick": a.tick, "kind": a.kind, "reason": a.reason}
                for a in ap.actions
            ]
        ),
        "fleet_size_final": len(fe.replicas),
        "swap": swap,
        "swap_at_tick": swap_tick,
        "swap_state": swap_status["state"],
        "swap_verdict": swap_status.get("verdict"),
        "swap_rollbacks": s["swap_rollbacks"],
        "finished": s["finished"],
        "retries": s["retries"],
        "replica_deaths": s["replica_deaths"],
        "watchdog_degraded": s["watchdog_degraded"],
        "watchdog_kills": s["watchdog_kills"],
        "restarts": s["restarts"],
        "restart_failures": s["restart_failures"],
        "probation_promotions": s["probation_promotions"],
        "probation_demotions": s["probation_demotions"],
        "replica_restarts": {h.replica_id: h.restarts for h in handles},
        "served_after_restart": served_after_restart,
        "final_health": {h.replica_id: h.health for h in handles},
        "bitwise_exact": all(
            o.status == "finished" and list(o.tokens) == list(r)
            for o, r in zip(outs, refs)
        ),
        "all_terminal": all(o.done for o in outs),
        "invariants_ok": not violations,
        "violations": violations,
    }
    return record, violations


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--new", type=int, default=0,
                    help="tokens per request (0 = backend default)")
    ap.add_argument("--router", type=str, default="least")
    ap.add_argument("--horizon", type=int, default=64,
                    help="fault-schedule tick horizon")
    ap.add_argument("--max-ticks", type=int, default=4000)
    ap.add_argument("--swap", action="store_true",
                    help="arm the seeded swap@T operator event: a "
                         "null-value rolling weight swap collides with "
                         "the storm and must resolve without wedging")
    ap.add_argument("--autopilot", action="store_true",
                    help="arm the SLO autopilot in scale-only trim "
                         "(shedding pinned off): autoscaling collides "
                         "with the storm — and any mid-swap scale is "
                         "typed-refused — under the same invariants")
    ap.add_argument("--autopilot-queue-age-target", type=float,
                    default=None,
                    help="autopilot breach target in seconds (default "
                         "8 x dt); lower it to force scale activity "
                         "in small storms")
    ap.add_argument("--record", type=str, default="",
                    help="write the soak record to this JSON file")
    args = ap.parse_args()

    from tpu_parallel.models import GPTLM, gpt2_125m, tiny_test

    on_tpu = jax.default_backend() == "tpu"
    cfg = (
        gpt2_125m(dropout_rate=0.0, remat=False)
        if on_tpu
        else tiny_test(remat=False)
    )
    new_tokens = args.new or (32 if on_tpu else 8)
    model = GPTLM(cfg)
    rnd = random.Random(args.seed)
    lo, hi = 3, min(16, cfg.seq_len - new_tokens - 2)
    prompts = make_prompts(cfg, rnd, args.requests, lo, hi)
    probe = jax.numpy.zeros((1, hi), jax.numpy.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]

    refs = baseline_tokens(model, params, prompts, new_tokens, args.slots)
    record, violations = run_soak(
        model, params, cfg, prompts, refs, seed=args.seed,
        n_replicas=args.replicas, n_slots=args.slots,
        new_tokens=new_tokens, router=args.router, horizon=args.horizon,
        max_ticks=args.max_ticks, swap=args.swap,
        autopilot=args.autopilot,
        autopilot_queue_age_target=args.autopilot_queue_age_target,
    )
    print(json.dumps(record, indent=2))
    if args.record:
        with open(args.record, "w") as fh:
            json.dump(record, fh, indent=2)
            fh.write("\n")
        print(f"record: {args.record}")
    if violations:
        print(
            f"chaos_bench: {len(violations)} INVARIANT VIOLATION(S)",
            file=sys.stderr,
        )
        sys.exit(1)
    print("chaos_bench: all invariants held")


if __name__ == "__main__":
    main()
