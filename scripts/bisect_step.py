"""Attribute full-step time across components by substitution.

The axon transport captures no xplane op events, so per-op profiling is
unavailable; this script bisects instead: it times the full train step with
attention swapped between {xla, flash, none} (``none`` passes V through,
keeping every shape and the surrounding projections identical), which yields
the *in-model* cost of each attention implementation by subtraction.

Usage: python scripts/bisect_step.py [batch] [remat] [variants...]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def run_one(batch, remat, attn_variant, steps=12):
    import tpu_parallel.models.layers as layers
    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig
    from tpu_parallel.utils.profiling import sync

    orig = layers.causal_attention
    attn_impl = "xla"
    if attn_variant == "flash":
        attn_impl = "flash"
    elif attn_variant == "none":
        layers.causal_attention = lambda q, k, v, segment_ids=None, window=0: v
    elif attn_variant != "xla":
        raise ValueError(f"unknown attention variant: {attn_variant!r}")

    overrides = dict(dropout_rate=0.0, attn_impl=attn_impl)
    if remat in ("dots", "proj", "proj_attn"):
        overrides.update(remat=True, remat_policy=remat)
    else:
        overrides.update(remat=remat in ("1", "full"))
    try:
        config = TrainerConfig(
            model="gpt2_125m",
            model_overrides=overrides,
            mesh=MeshConfig(data=-1),
            global_batch_size=batch,
            steps=steps,
            log_every=10_000,
            donate=True,
        )
        trainer = Trainer(config)
        trainer.init()
        state, metrics = trainer.state, None
        for _ in range(3):
            state, metrics = trainer.funcs.step_fn(
                state, metrics, trainer.example_batch
            )
        sync((state, metrics))
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = trainer.funcs.step_fn(
                state, metrics, trainer.example_batch
            )
        sync((state, metrics))
        dt = (time.perf_counter() - t0) / steps
        print(
            json.dumps(
                {
                    "batch": batch,
                    "remat": remat,
                    "attn": attn_variant,
                    "step_ms": round(dt * 1e3, 2),
                }
            ),
            flush=True,
        )
    except Exception as e:
        print(
            json.dumps(
                {"batch": batch, "remat": remat, "attn": attn_variant,
                 "error": repr(e)[:140]}
            ),
            flush=True,
        )
    finally:
        layers.causal_attention = orig


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    remat = sys.argv[2] if len(sys.argv) > 2 else "proj"
    variants = sys.argv[3:] or ["xla", "flash", "none"]
    for v in variants:
        run_one(batch, remat, v)


if __name__ == "__main__":
    main()
