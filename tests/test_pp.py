"""Pipeline-parallel tests: GPipe schedule correctness and end-to-end training.

The reference has zero pipeline logic to mirror (its pipeline_parallel.py is
an import-only stub), so these tests define the contract from scratch:
(1) the pipelined forward equals sequentially composing the per-stage modules,
(2) a PP classifier trains end-to-end on a pipe x data mesh.
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import compute
from tpu_parallel.core.rng import fold_rng_over_axis
from tpu_parallel.parallel import pp
from tpu_parallel.parallel.spmd import build_train_functions, make_model_init
from tpu_parallel.core.state import Batch, TrainState
from tpu_parallel.data import classification_batch

DIM = 16


class _Block(nn.Module):
    """A residual stage block (shape-preserving, as pipeline stages must be)."""

    @nn.compact
    def __call__(self, x, train: bool = True):
        h = nn.Dense(DIM)(x)
        h = nn.silu(h)
        return x + h


def test_pipeline_forward_equals_sequential(mesh_pipe4_data2, rng):
    """Pipelined forward == applying the 4 stage modules one after another."""
    x = jax.random.normal(jax.random.PRNGKey(0), (8, DIM))
    model = pp.PipelineModule(
        stage_fn=_Block, num_microbatches=4, axis_name="pipe", broadcast_outputs=True
    )

    def body(rng, x):
        variables = model.init({"params": rng}, x)
        out = model.apply(variables, x)
        return variables["params"], out

    probe = jax.shard_map(
        body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
        out_specs=P(), check_vma=False,
    )
    shapes = jax.eval_shape(probe, rng, x)
    specs = nn.get_partition_spec(shapes)[0]
    f = jax.jit(
        jax.shard_map(
            body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
            out_specs=(specs, P("data", None)), check_vma=False,
        )
    )
    params, out = f(rng, x)

    # Assemble per-stage weights ([4, DIM, DIM] kernels) and compose manually.
    stage_params = params["stage"]["sharded"]
    kernel = np.asarray(stage_params["Dense_0"]["kernel"].value)  # [4, DIM, DIM]
    bias = np.asarray(stage_params["Dense_0"]["bias"].value)  # [4, DIM]
    ref = np.asarray(x)
    for s in range(4):
        ref = ref + np.asarray(jax.nn.silu(jnp.asarray(ref @ kernel[s] + bias[s])))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_pipeline_stage_params_differ(mesh_pipe4_data2, rng):
    """RNG folding must give each pipe rank independent stage weights."""
    x = jnp.zeros((8, DIM))
    model = pp.PipelineModule(stage_fn=_Block, num_microbatches=2)

    def body(rng, x):
        return model.init({"params": rng}, x)["params"]

    probe = jax.shard_map(
        body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
        out_specs=P(), check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, x))
    f = jax.jit(
        jax.shard_map(
            body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
            out_specs=specs, check_vma=False,
        )
    )
    params = f(rng, x)
    kernel = np.asarray(params["stage"]["sharded"]["Dense_0"]["kernel"].value)
    for a in range(4):
        for b in range(a + 1, 4):
            assert not np.allclose(kernel[a], kernel[b]), f"stages {a},{b} identical"


class _DropoutBlock(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        h = nn.Dense(DIM)(x)
        h = nn.Dropout(rate=0.5, deterministic=not train)(h)
        return x + h


def test_pipeline_forwards_kwargs_to_stages(mesh_pipe4_data2, rng):
    """train=False must reach the stage modules: eval is deterministic."""
    x = jax.random.normal(jax.random.PRNGKey(0), (8, DIM))
    model = pp.PipelineModule(
        stage_fn=_DropoutBlock, num_microbatches=4, broadcast_outputs=True
    )

    def body(rng, drng, x):
        variables = model.init({"params": rng}, x, train=False)
        return model.apply(variables, x, train=False, rngs={"dropout": drng})

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh_pipe4_data2,
            in_specs=(P(), P(), P("data", None)),
            out_specs=P("data", None),
            check_vma=False,
        )
    )
    out1 = f(rng, jax.random.PRNGKey(1), x)
    out2 = f(rng, jax.random.PRNGKey(2), x)
    # different dropout rngs, identical outputs <=> dropout actually disabled
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_indivisible_microbatches_raise(mesh_pipe4_data2, rng):
    model = pp.PipelineModule(stage_fn=_Block, num_microbatches=3)
    x = jnp.zeros((8, DIM))  # 8 % 3 != 0

    def body(rng, x):
        return model.init({"params": rng}, x)["params"]

    with pytest.raises(ValueError, match="not divisible"):
        jax.eval_shape(
            jax.shard_map(
                body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
                out_specs=P(), check_vma=False,
            ),
            rng,
            x,
        )


class _PPClassifier(nn.Module):
    """Embed -> pipelined residual blocks -> head, loss valid on last rank."""

    num_classes: int = 10
    num_microbatches: int = 4

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Dense(DIM, name="embed")(x)
        x = pp.PipelineModule(
            stage_fn=_Block, num_microbatches=self.num_microbatches, name="pipeline"
        )(x, train=train)
        return nn.Dense(self.num_classes, name="head")(x).astype(jnp.float32)


def _pp_loss(params, apply_fn, batch, rng):
    dropout_rng = fold_rng_over_axis(rng, ("data", "pipe"))
    logits = apply_fn({"params": params}, batch.inputs, rngs={"dropout": dropout_rng})
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, batch.labels)
    mask = pp.last_stage_mask("pipe")
    correct = (logits.argmax(-1) == batch.labels).astype(jnp.float32)
    bs = jnp.float32(batch.labels.size)
    metrics = {
        "loss": ((loss * mask).sum(), bs * mask),
        "accuracy": ((correct * mask).sum(), bs * mask),
    }
    return (loss * mask).mean(), metrics


def test_pp_replicated_params_stay_consistent(mesh_pipe4_data2, rng):
    """Embed/head params are replicated over pipe but only one rank produces
    their gradient; grad_psum_axes=('pipe',) must keep all ranks bit-identical
    (without it they silently diverge)."""
    batch = classification_batch(jax.random.PRNGKey(5), 32, DIM, 10)
    model = _PPClassifier()
    init = make_model_init(model, optax.adamw(1e-3), train_arg=True)
    funcs = build_train_functions(
        init,
        _pp_loss,
        mesh_pipe4_data2,
        batch,
        grad_sync_axes=("data",),
        grad_psum_axes=("pipe",),
        metric_axes=("data", "pipe"),
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    for _ in range(5):
        state, _ = funcs.step_fn(state, None, batch)
    read = jax.jit(
        jax.shard_map(
            lambda s: s.params["embed"]["kernel"][None],
            mesh=mesh_pipe4_data2,
            in_specs=(funcs.state_specs,),
            out_specs=P("pipe"),
            check_vma=False,
        )
    )
    per_rank = np.asarray(read(state))
    for i in range(1, 4):
        np.testing.assert_array_equal(per_rank[i], per_rank[0])


def test_pp_training_loss_decreases(mesh_pipe4_data2, rng):
    batch = classification_batch(jax.random.PRNGKey(3), 32, DIM, 10)
    model = _PPClassifier()
    init = make_model_init(model, optax.adamw(1e-3), train_arg=True)
    funcs = build_train_functions(
        init,
        _pp_loss,
        mesh_pipe4_data2,
        batch,
        batch_spec=P("data"),
        grad_sync_axes=("data",),
        grad_psum_axes=("pipe",),
        metric_axes=("data", "pipe"),
        num_minibatches=1,
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)["loss"]
    for _ in range(15):
        state, m = funcs.step_fn(state, None, batch)
    last = compute(m)["loss"]
    assert last < first, f"PP loss did not decrease: {first} -> {last}"
    # metric counts: 32-sample global batch, only last pipe rank contributes
    assert float(m["loss"][1]) == 32.0


def test_interleaved_pipeline_matches_sequential(rng):
    """Circular schedule (pipe=2, interleave=2): gradients match the no-PP
    twin on the same logical 4-layer model (chunk c = layer c lives on rank
    c%2 as virtual stage c//2)."""
    import flax.linen as nn
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.data import lm_batch
    from tpu_parallel.models import GPTLM, make_gpt_loss, tiny_test
    from tpu_parallel.parallel import fsdp
    from tpu_parallel.runtime import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=4, pipe=2))
    num_mb = 2
    common = dict(dtype=jnp.float32, remat=False, num_microbatches=num_mb)
    cfg1 = tiny_test(**common)
    cfgI = tiny_test(**common, pipe_size=2, pipe_interleave=2)
    model1, modelI = GPTLM(cfg1), GPTLM(cfgI)
    loss1 = make_gpt_loss(cfg1, train=False)
    lossI = make_gpt_loss(cfgI, train=False)
    batch = lm_batch(jax.random.PRNGKey(0), 8, cfg1.seq_len, cfg1.vocab_size)

    def make_init(model):
        def init(r, b):
            return model.init({"params": r}, b.tokens, train=False)["params"]

        return init

    def specs_and_params(model):
        probe = jax.shard_map(
            make_init(model), mesh=mesh, in_specs=(P(), P("data")),
            out_specs=P(), check_vma=False,
        )
        specs = nn.get_partition_spec(jax.eval_shape(probe, rng, batch))
        real = jax.jit(
            jax.shard_map(
                make_init(model), mesh=mesh, in_specs=(P(), P("data")),
                out_specs=specs, check_vma=False,
            )
        )(rng, batch)
        return specs, real

    specs1, params1 = specs_and_params(model1)
    specsI, _ = specs_and_params(modelI)

    # Transplant: no-PP scan-stacked layers [4, ...].  chunk j on rank r is
    # layer j*pipe + r, so chunk{j}'s pipe-stacked params are layers
    # [j*2 : j*2+2] reshaped [pipe, 1(scan), ...].
    def slice_to_chunk(j):
        def cut(x):
            if isinstance(x, nn.Partitioned):
                v, names = x.value, x.names
            else:
                v, names = x, (None,) * x.ndim
            v = v[j * 2 : (j + 1) * 2]
            return nn.Partitioned(
                v.reshape(2, 1, *v.shape[1:]), ("pipe",) + tuple(names)
            )

        return cut

    blocks = dict(params1)["blocks"]
    paramsI = {k: v for k, v in params1.items() if k != "blocks"}
    paramsI["pipeline"] = {
        "stage": {
            "sharded": {
                f"chunk{j}": jax.tree_util.tree_map(
                    slice_to_chunk(j),
                    blocks,
                    is_leaf=lambda x: isinstance(x, nn.Partitioned),
                )
                for j in range(2)
            }
        }
    }

    def grads_nopp(params, b, r):
        total = None
        mb_size = b.tokens.shape[0] // num_mb
        for i in range(num_mb):
            mb = jax.tree_util.tree_map(
                lambda a: a[i * mb_size : (i + 1) * mb_size], b
            )
            g = jax.grad(lambda p: loss1(p, model1.apply, mb, r)[0])(params)
            total = g if total is None else jax.tree_util.tree_map(
                jnp.add, total, g
            )
        g = jax.tree_util.tree_map(lambda x: x / num_mb, total)
        return fsdp.sync_gradients(g, ("data",))

    def grads_pp(params, b, r):
        g = jax.grad(lambda p: lossI(p, modelI.apply, b, r)[0])(params)
        return fsdp.sync_gradients(g, ("data",))

    g1 = jax.jit(
        jax.shard_map(
            grads_nopp, mesh=mesh, in_specs=(specs1, P("data"), P()),
            out_specs=specs1, check_vma=False,
        )
    )(params1, batch, rng)
    gI = jax.jit(
        jax.shard_map(
            grads_pp, mesh=mesh, in_specs=(specsI, P("data"), P()),
            out_specs=specsI, check_vma=False,
        )
    )(paramsI, batch, rng)

    def unbox(x):
        return np.asarray(x.value if isinstance(x, nn.Partitioned) else x)

    # every layer's qkv gradient must match its chunk's
    want_all = unbox(
        g1["blocks"]["layers"]["block"]["attn"]["qkv"]["shard"]["sharded"]["kernel"]
    )  # [4, 1, d, 3d]
    for j in range(2):
        got = unbox(
            gI["pipeline"]["stage"]["sharded"][f"chunk{j}"]["layers"]["block"][
                "attn"
            ]["qkv"]["shard"]["sharded"]["kernel"]
        )  # [2(pipe), 1(scan), 1, d, 3d]
        want = want_all[j * 2 : (j + 1) * 2]
        np.testing.assert_allclose(
            got.reshape(want.shape), want, rtol=2e-4, atol=1e-6,
            err_msg=f"chunk{j}",
        )
    # embedding grads flow through the full interleaved backward
    np.testing.assert_allclose(
        unbox(gI["embed"]["tok"]["embedding"]),
        unbox(g1["embed"]["tok"]["embedding"]),
        rtol=2e-4, atol=1e-6,
    )


# --- packed sequences under PP -----------------------------------------------


@pytest.mark.parametrize("interleave", [1, 2])
def test_pp_packed_loss_equals_unpacked(mesh_2x2x2, rng, interleave):
    """Two length-16 documents packed into one 32-token row (segment ids +
    restarting positions) produce the same mean loss as the two rows
    unpacked — attention may not cross the packing boundary, positions must
    restart, and both must survive the microbatch split + schedule."""
    import optax  # noqa: F401

    from tpu_parallel.core.state import TextBatch
    from tpu_parallel.models import GPTLM, make_gpt_loss, tiny_test

    cfg = tiny_test(
        positional="rope",  # no absolute-slot dependence: packing-invariant
        pipe_size=2,
        pipe_interleave=interleave,
        num_microbatches=2,
        remat=False,
        dtype=jnp.float32,
    )
    model = GPTLM(cfg)
    loss_fn = make_gpt_loss(cfg)
    mesh = mesh_2x2x2

    docs = jax.random.randint(rng, (8, 16), 0, cfg.vocab_size)
    tgts = jax.random.randint(jax.random.PRNGKey(5), (8, 16), 0, cfg.vocab_size)
    arange16 = jnp.broadcast_to(jnp.arange(16), (4, 16))
    packed = TextBatch(
        tokens=docs.reshape(4, 32),
        targets=tgts.reshape(4, 32),
        loss_mask=jnp.ones((4, 32), jnp.float32),
        positions=jnp.concatenate([arange16, arange16], axis=1),
        segment_ids=jnp.concatenate(
            [jnp.zeros((4, 16), jnp.int32), jnp.ones((4, 16), jnp.int32)], axis=1
        ),
    )
    unpacked = TextBatch(
        tokens=docs,
        targets=tgts,
        loss_mask=jnp.ones((8, 16), jnp.float32),
        positions=jnp.broadcast_to(jnp.arange(16), (8, 16)),
        segment_ids=None,
    )

    def init(r, tokens):
        return model.init({"params": r}, tokens, train=False)["params"]

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, packed.tokens))
    params = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, packed.tokens)

    def mean_loss(params, batch, rng_):
        _, metrics = loss_fn(params, model.apply, batch, rng_)
        s, c = metrics["loss"]
        s = jax.lax.psum(s, ("data", "pipe", "model"))
        c = jax.lax.psum(c, ("data", "pipe", "model"))
        return s / c

    losses = {}
    for name, batch in (("packed", packed), ("unpacked", unpacked)):
        f = jax.jit(
            jax.shard_map(
                mean_loss, mesh=mesh, in_specs=(specs, P("data"), P()),
                out_specs=P(), check_vma=False,
            )
        )
        losses[name] = float(f(params, batch, jax.random.PRNGKey(0)))
    assert abs(losses["packed"] - losses["unpacked"]) < 2e-4, losses


def test_pp_packed_leakage_blocked(mesh_pipe4_data2, rng):
    """Under PP, perturbing segment 0's tokens must not change segment 1's
    loss contribution (cross-document attention blocked through the
    microbatch split and schedule)."""
    from tpu_parallel.core.state import TextBatch
    from tpu_parallel.models import GPTLM, make_gpt_loss, tiny_test

    cfg = tiny_test(
        positional="rope", pipe_size=4, num_microbatches=2, remat=False,
        dtype=jnp.float32,
    )
    model = GPTLM(cfg)
    loss_fn = make_gpt_loss(cfg)
    mesh = mesh_pipe4_data2

    tokens = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(5), (4, 32), 0, cfg.vocab_size)
    arange16 = jnp.broadcast_to(jnp.arange(16), (4, 16))
    seg = jnp.concatenate(
        [jnp.zeros((4, 16), jnp.int32), jnp.ones((4, 16), jnp.int32)], axis=1
    )
    positions = jnp.concatenate([arange16, arange16], axis=1)
    # mask the loss to segment 1 only, then perturb segment 0's tokens
    seg1_mask = (seg == 1).astype(jnp.float32)

    def make_batch(toks):
        return TextBatch(
            tokens=toks, targets=targets, loss_mask=seg1_mask,
            positions=positions, segment_ids=seg,
        )

    def init(r, tokens):
        return model.init({"params": r}, tokens, train=False)["params"]

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, tokens))
    params = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, tokens)

    def mean_loss(params, batch, rng_):
        _, metrics = loss_fn(params, model.apply, batch, rng_)
        s, c = metrics["loss"]
        s = jax.lax.psum(s, ("data", "pipe"))
        c = jax.lax.psum(c, ("data", "pipe"))
        return s / c

    f = jax.jit(
        jax.shard_map(
            mean_loss, mesh=mesh, in_specs=(specs, P("data"), P()),
            out_specs=P(), check_vma=False,
        )
    )
    base = float(f(params, make_batch(tokens), jax.random.PRNGKey(0)))
    perturbed_toks = tokens.at[:, :16].set(
        (tokens[:, :16] + 7) % cfg.vocab_size
    )
    pert = float(f(params, make_batch(perturbed_toks), jax.random.PRNGKey(0)))
    assert abs(base - pert) < 1e-6, (base, pert)


# --- 1F1B schedule -------------------------------------------------------------


@pytest.mark.parametrize("fsdp_on", [False, True])
def test_1f1b_matches_gpipe(mesh_2x2x2, rng, fsdp_on):
    """The 1F1B schedule (gradients computed inside the interleaved
    fwd/bwd scan — pp.pipeline_1f1b_grads) reproduces GPipe's gradients
    leaf-for-leaf (rtol 1e-5: same math, different schedule) and its loss
    trajectory over 3 Trainer steps, on a pipe x data x model mesh, with
    and without FSDP param sharding.  Pins the whole chain: schedule
    masks, saved-input ring buffer, cotangent ring and its automatic
    model-axis reduction, per-rank grad masking, token normalization, and
    the pipe-psum grad sync.  (Parameters after several adam steps are
    NOT compared bitwise: adam divides by sqrt(second moment), amplifying
    float summation-order noise early in training.)"""
    import optax

    from tpu_parallel.core.accumulate import accumulate_gradients
    from tpu_parallel.core.state import TextBatch, TrainState
    from tpu_parallel.models import GPTLM, make_gpt_loss, tiny_test
    from tpu_parallel.models.gpt import make_gpt_1f1b_grad_fn
    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig

    mesh = mesh_2x2x2
    overrides = dict(
        pipe_size=2,
        num_microbatches=4,
        dtype=jnp.float32,
        remat=False,
        dropout_rate=0.0,
    )
    if fsdp_on:
        overrides.update(fsdp=True, fsdp_min_size=0)

    # --- direct gradient parity on one batch ------------------------------
    cfg = tiny_test(**overrides)
    model = GPTLM(cfg)
    loss_fn = make_gpt_loss(cfg)
    grad_1f1b = make_gpt_1f1b_grad_fn(cfg)
    tx = optax.adamw(1e-3)
    toks = jax.random.randint(rng, (8, 32), 0, cfg.vocab_size)
    batch = TextBatch(tokens=toks, targets=jnp.roll(toks, -1, 1))

    def init(r, b):
        v = model.init({"params": r}, b.tokens, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=r
        )

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, batch))
    state = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, batch)

    def g_gpipe(state, b, r):
        grads, _ = accumulate_gradients(state, b, r, 1, loss_fn, use_scan=False)
        return grads

    def g_1f1b(state, b, r):
        grads, _ = grad_1f1b(state.params, b, r)
        return grads

    out = {}
    for name, f in (("gpipe", g_gpipe), ("1f1b", g_1f1b)):
        fn = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(specs, P("data"), P()),
                out_specs=specs.params, check_vma=False,
            )
        )
        out[name] = jax.device_get(fn(state, batch, jax.random.PRNGKey(7)))

    def unbox(t):
        return jax.tree_util.tree_map(
            lambda x: x.value if isinstance(x, nn.Partitioned) else x,
            t,
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )

    flat_g = jax.tree_util.tree_leaves_with_path(unbox(out["gpipe"]))
    flat_f = jax.tree_util.tree_leaves(unbox(out["1f1b"]))
    for (path, leaf_g), leaf_f in zip(flat_g, flat_f):
        np.testing.assert_allclose(
            np.asarray(leaf_g), np.asarray(leaf_f), rtol=1e-5, atol=1e-6,
            err_msg=jax.tree_util.keystr(path),
        )

    # --- end-to-end: Trainer loss trajectory ------------------------------
    losses = {}
    for sched in ("gpipe", "1f1b"):
        config = TrainerConfig(
            model="tiny",
            model_overrides=dict(overrides, pipe_schedule=sched),
            mesh=MeshConfig(pipe=2, data=2, model=2),
            global_batch_size=8,
            steps=3,
            log_every=1000,
            donate=False,
            seed=0,
        )
        trainer = Trainer(config)
        trainer.init()
        losses[sched] = trainer.train(steps=3)["loss"]
    assert abs(losses["gpipe"] - losses["1f1b"]) < 1e-4, losses


def test_1f1b_deep_schedule_matches_gpipe(mesh_pipe4_data2, rng):
    """Gradient parity at pipe=4 with num_microbatches=12 — the
    many-microbatch regime 1F1B exists for, where the saved-input ring
    buffer wraps several times (in-flight lag on rank 0 is 2n-2 = 6
    ticks; the 2n-1 = 7-slot ring must never overwrite a slot before its
    backward replays it).  A ring one slot too small fails this test with
    grossly wrong stage gradients, not a subtle drift."""
    import optax

    from tpu_parallel.core.accumulate import accumulate_gradients
    from tpu_parallel.core.state import TextBatch, TrainState
    from tpu_parallel.models import GPTLM, make_gpt_loss, tiny_test
    from tpu_parallel.models.gpt import make_gpt_1f1b_grad_fn

    mesh = mesh_pipe4_data2
    cfg = tiny_test(
        pipe_size=4, num_microbatches=12, dtype=jnp.float32, remat=False,
        dropout_rate=0.0,
    )
    model = GPTLM(cfg)
    loss_fn = make_gpt_loss(cfg)
    grad_1f1b = make_gpt_1f1b_grad_fn(cfg)
    tx = optax.adamw(1e-3)
    toks = jax.random.randint(rng, (24, 32), 0, cfg.vocab_size)
    batch = TextBatch(tokens=toks, targets=jnp.roll(toks, -1, 1))

    def init(r, b):
        v = model.init({"params": r}, b.tokens, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=r
        )

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, batch))
    state = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, batch)

    def g_gpipe(state, b, r):
        grads, _ = accumulate_gradients(state, b, r, 1, loss_fn, use_scan=False)
        return grads

    def g_1f1b(state, b, r):
        grads, _ = grad_1f1b(state.params, b, r)
        return grads

    out = {}
    for name, f in (("gpipe", g_gpipe), ("1f1b", g_1f1b)):
        fn = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(specs, P("data"), P()),
                out_specs=specs.params, check_vma=False,
            )
        )
        out[name] = jax.device_get(fn(state, batch, jax.random.PRNGKey(3)))

    def unbox(t):
        return jax.tree_util.tree_map(
            lambda x: x.value if isinstance(x, nn.Partitioned) else x,
            t,
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )

    flat_g = jax.tree_util.tree_leaves_with_path(unbox(out["gpipe"]))
    flat_f = jax.tree_util.tree_leaves(unbox(out["1f1b"]))
    for (path, leaf_g), leaf_f in zip(flat_g, flat_f):
        np.testing.assert_allclose(
            np.asarray(leaf_g), np.asarray(leaf_f), rtol=1e-5, atol=1e-6,
            err_msg=jax.tree_util.keystr(path),
        )


def test_1f1b_bf16_traces_and_trains(mesh_2x2x2):
    """bf16 (the production dtype): the schedule's two rings and the
    saved-input buffer must carry bf16 cotangents without a carry-dtype
    mismatch, and a Trainer step must run.  (No parity assertion: bf16
    summation noise swamps tight tolerances.)"""
    del mesh_2x2x2
    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig

    config = TrainerConfig(
        model="tiny",
        model_overrides=dict(
            pipe_size=2, num_microbatches=4, dtype=jnp.bfloat16,
            remat=False, dropout_rate=0.0, pipe_schedule="1f1b",
        ),
        mesh=MeshConfig(pipe=2, data=2, model=2),
        global_batch_size=8,
        steps=2,
        log_every=1000,
        donate=False,
        seed=0,
    )
    trainer = Trainer(config)
    trainer.init()
    res = trainer.train(steps=2)
    assert res["loss"] > 0, res
