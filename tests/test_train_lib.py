"""Trainer / config-system tests."""

import jax
import jax.numpy as jnp
import pytest

from tpu_parallel.runtime import MeshConfig
from tpu_parallel.train_lib import Trainer, TrainerConfig


def test_trainer_tiny_3d(devices):
    config = TrainerConfig(
        model="tiny",
        model_overrides=dict(num_microbatches=2),
        mesh=MeshConfig(data=2, model=2, pipe=2),
        global_batch_size=16,
        steps=8,
        log_every=4,
        donate=False,
    )
    trainer = Trainer(config)
    assert trainer.model_config.pipe_size == 2  # mesh dictates pipeline degree
    trainer.init()
    logs = []
    result = trainer.train(log_fn=lambda step, m: logs.append((step, m)))
    assert result["loss"] > 0
    assert result["tokens_per_sec"] > 0
    assert logs and logs[-1][0] == 8


def test_trainer_from_config_dict(devices):
    from ml_collections import ConfigDict

    cd = ConfigDict(
        dict(
            model="tiny",
            model_overrides=ConfigDict(),
            mesh=ConfigDict(dict(data=8, model=1, pipe=1, seq=1)),
            global_batch_size=16,
            num_minibatches=2,
            steps=2,
            learning_rate=1e-3,
            warmup_steps=1,
            weight_decay=0.0,
            grad_clip=1.0,
            seed=1,
            log_every=1,
            donate=False,
        )
    )
    config = TrainerConfig.from_config_dict(cd)
    assert config.mesh.data == 8
    trainer = Trainer(config)
    result = trainer.train()
    assert result["loss"] > 0


def test_trainer_rejects_indivisible_batch(devices):
    config = TrainerConfig(
        model="tiny", mesh=MeshConfig(data=8), global_batch_size=12
    )
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(config)


def test_trainer_num_params(devices):
    config = TrainerConfig(
        model="tiny", mesh=MeshConfig(data=8), global_batch_size=16
    )
    trainer = Trainer(config)
    n = trainer.num_params
    assert 1e4 < n < 1e6


def test_trainer_seq_parallel_ring():
    """Trainer wires a >1 seq axis end-to-end: tokens sharded P("data","seq"),
    ring attention over the seq axis, loss decreasing."""
    from tpu_parallel.runtime import MeshConfig

    config = TrainerConfig(
        model="tiny",
        model_overrides=dict(attn_impl="ring", seq_len=64),
        mesh=MeshConfig(data=2, seq=2, model=2),
        global_batch_size=8,
        steps=6,
        log_every=6,
        learning_rate=1e-2,
        donate=False,
    )
    trainer = Trainer(config)
    assert str(trainer.batch_spec) == "PartitionSpec('data', 'seq')"
    # regression: num_params' mesh-free abstract init must not trace ring
    # attention (psum on the unbound seq axis) — train.py logs it at startup
    assert trainer.num_params > 0
    result = trainer.train()
    assert result["loss"] > 0 and result["accuracy"] >= 0
    first = trainer.train(steps=1)  # continues from trained state
    assert first["loss"] < result["loss"] * 1.5  # sanity: not diverging


def test_trainer_rejects_seq_mesh_with_dense_attention():
    from tpu_parallel.runtime import MeshConfig

    with pytest.raises(ValueError, match="attn_impl"):
        Trainer(
            TrainerConfig(
                model="tiny",
                mesh=MeshConfig(data=4, seq=2),
                global_batch_size=8,
            )
        )


def test_ema_params_track_and_eval():
    """EMA shadow follows params by the decay rule and evaluation uses it."""
    import numpy as np
    from tpu_parallel.runtime import MeshConfig

    d = 0.5  # aggressive decay so two steps produce a visible gap
    config = TrainerConfig(
        model="tiny",
        mesh=MeshConfig(data=-1),
        global_batch_size=16,
        steps=4,
        ema_decay=d,
        learning_rate=1e-2,
        log_every=10,
        donate=False,
    )
    trainer = Trainer(config)
    trainer.init()
    state = trainer.state
    assert state.ema_params is not None

    # manual shadow: replay the decay rule alongside two real steps
    unbox = lambda t: jax.tree_util.tree_map(
        lambda x: x.value if hasattr(x, "value") else x, t,
        is_leaf=lambda x: hasattr(x, "value"),
    )
    ema = jax.tree_util.tree_map(jnp.asarray, unbox(state.ema_params))
    for _ in range(2):
        state, _ = trainer.funcs.step_fn(state, None, trainer.example_batch)
        ema = jax.tree_util.tree_map(
            lambda e, p: e * d + p.astype(e.dtype) * (1 - d), ema, unbox(state.params)
        )
    for (path, got), (_, want) in zip(
        jax.tree_util.tree_leaves_with_path(unbox(state.ema_params)),
        jax.tree_util.tree_leaves_with_path(ema),
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6,
            err_msg=str(path),
        )

    # ema differs from the live params (training moved them)
    diffs = jax.tree_util.tree_map(
        lambda e, p: float(jnp.max(jnp.abs(e - p))), unbox(state.ema_params),
        unbox(state.params),
    )
    assert max(jax.tree_util.tree_leaves(diffs)) > 0

    # eval runs against the shadow without error
    trainer.state = state
    result = trainer.evaluate(steps=1)
    assert "loss" in result


def test_trainer_seq2seq_family():
    """The registry dispatches Seq2SeqConfig factories to the
    encoder-decoder wiring: EncoderDecoder model, teacher-forced CE,
    synthetic seq2seq batches — same Trainer surface (train + evaluate)."""
    from tpu_parallel.runtime import MeshConfig

    tr = Trainer(
        TrainerConfig(
            model="tiny_seq2seq",
            mesh=MeshConfig(data=4, model=2),
            global_batch_size=16,
            steps=6,
            log_every=100,
            objective="seq2seq",
        )
    )
    tr.init()
    first = tr.evaluate(steps=1)["loss"]
    m = tr.train()
    assert m["loss"] < first
    assert "tokens_per_sec" in m
    ev = tr.evaluate(steps=2)
    assert ev["loss"] < first


def test_trainer_seq2seq_rejects_single_stack_objective():
    from tpu_parallel.runtime import MeshConfig

    with pytest.raises(ValueError, match="single-stack"):
        Trainer(
            TrainerConfig(
                model="tiny_seq2seq", mesh=MeshConfig(data=8), objective="mlm"
            )
        )
