"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head resharding.

Long-context capability complementing ring attention (no reference
equivalent — SURVEY.md §5).  Where ring attention keeps the sequence
sharded and rotates K/V around the mesh axis, Ulysses pays two
``lax.all_to_all`` reshards instead: gather the full sequence while
scattering heads, run ordinary (flash) attention per local head group, then
reshard back.  Communication is two all-to-alls of the activations per call
— cheaper than a full ring when heads >= axis size and the per-chip
sequence fits HBM; ring wins when the sequence itself must never
materialize on one chip.

Requires ``n_heads % axis_size == 0``.  GQA K/V pass at kv-head width:
when ``n_kv_heads % axis_size == 0`` they reshard as-is (group-times less
all_to_all volume — the q->kv routing is preserved shard-locally);
otherwise the op expands them to full width internally.  Do NOT
pre-expand K/V before calling.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


@jax.named_scope("ulysses_attention")
def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    attn_fn: Optional[Callable] = None,
    segment_ids=None,
) -> jax.Array:
    """Attention on seq-sharded [batch, local_seq, heads, head_dim].

    Must run inside a ``shard_map`` region binding ``axis_name``.  The inner
    ``attn_fn`` (default: the flash kernel via its own dispatch) sees
    [batch, full_seq, heads/n, head_dim] — contiguous global sequence, so
    plain causal masking is correct.
    """
    n = lax.psum(1, axis_name)
    h = q.shape[2]
    h_kv = k.shape[2]
    if h % n != 0:
        raise ValueError(f"n_heads={h} not divisible by seq axis size {n}")
    if h_kv != h:
        # grouped-query K/V: when the kv heads split evenly over the axis,
        # reshard them at kv width — the q->kv head routing is preserved
        # shard-locally (q head i and kv head i//group land on the same
        # rank, local index i' -> i'//group), and the K/V all_to_all volume
        # drops by the group factor.  Otherwise expand to full heads first
        # (correct, full-width traffic).
        if h % h_kv != 0:
            raise ValueError(
                f"q heads {h} not a multiple of k/v heads {h_kv}"
            )
        if h_kv % n != 0:
            group = h // h_kv
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
    if attn_fn is None:
        # flash by default: the inner attention runs over the FULL gathered
        # sequence, so a naive softmax would materialize the [B, H/n, S, S]
        # scores this mode exists to avoid.  flash_attention streams K/V
        # blocks (and falls back to the reference path off-TPU / at tiny,
        # non-128-divisible sequence lengths).
        from tpu_parallel.ops.flash_attention import flash_attention

        attn_fn = flash_attention

    def gather_seq_scatter_heads(x):
        # [B, s/n, H, D] -> [B, s, H/n, D]; tiled all_to_all concatenates
        # the sequence chunks in rank order, restoring global order.
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = map(gather_seq_scatter_heads, (q, k, v))
    out = attn_fn(q, k, v, segment_ids=segment_ids)
    # [B, s, H/n, D] -> [B, s/n, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
