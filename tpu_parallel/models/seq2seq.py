"""Encoder-decoder (seq2seq) transformer family.

Completes the model-family triad next to the decoder-only LMs
(:mod:`~tpu_parallel.models.gpt`) and the bidirectional encoders
(``bidirectional=True`` + :func:`~tpu_parallel.models.gpt.make_mlm_loss`):
a T5-shaped architecture — bidirectional encoder over the source, causal
decoder over the target, cross-attention from every decoder layer into the
encoder's output.  No reference capability exists (the reference trains
2-layer MLPs only — SURVEY.md §2.4); this is framework surface the
reference's users would expect.

TPU-first choices, consistent with the rest of the family:

- Encoder and decoder reuse the same :class:`TPDense`-structured blocks
  (:class:`~tpu_parallel.models.layers.Block` /
  :class:`~tpu_parallel.models.layers.Attention`), so tensor parallelism is
  structural and FSDP wraps per-layer via ``fsdp.maybe_shard`` exactly as
  the LM stack does.
- Cross-attention is GQA-native (grouped queries contract against kv-width
  memory directly, like
  :func:`~tpu_parallel.models.layers.decode_attention`) and carries no
  positional transform: relative order enters through the self-attention
  paths on each side, the standard encoder-decoder convention.
- Decoding caches the projected memory K/V once at prefill (``cache``
  collection) — per-step cross-attention is two einsums against cached
  tensors, no re-projection of the source.
- The loss reuses :func:`~tpu_parallel.models.gpt.make_ce_fn`:
  vocab-parallel CE under TP, sequence-chunked under ``loss_chunk``,
  FSDP-gathered lm_head applied once.

Every mesh strategy composes.  Sequence parallelism: both stacks shard
their token axis with ring/Ulysses self-attention, and the seq-sharded
encoder memory is gathered once per decoder pass (outside the remat'd
stack) so sharded decoder queries see the whole source.  Pipeline
parallelism: each pipe rank owns enc_layers/pipe encoder blocks AND
n_layers/pipe decoder blocks as two sequential GPipe passes — the encoder
pipeline broadcasts its output, the decoder pipeline feeds it to every
stage's cross-attention as a per-microbatch extra.  MoE composes too:
routed experts replace the MLP in BOTH stacks (the original Switch
Transformer is exactly a T5-shaped MoE), expert-parallel over the model
axis, balance aux collected across encoder+decoder blocks.  Deliberate
refusals (loud, not silent): MoE under the pipelined schedule, the
post-norm/BERT knobs, relative bias under PP, and decoding under a bound
seq axis or pipe mesh.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from tpu_parallel.core.metrics import Metrics
from tpu_parallel.core.rng import fold_rng_over_axis
from tpu_parallel.models.gpt import (
    GPTConfig,
    _lm_head_params,
    _make_lm_head,
    make_ce_fn,
)
from tpu_parallel.models.layers import (
    MLP,
    Attention,
    BlockStack,
    Embedding,
    RelativePositionBias,
    make_norm,
    remat_kwargs_for,
    seq_parallel_active,
)
from tpu_parallel.parallel import fsdp
from tpu_parallel.parallel.tp import TPDense, axis_size_or_none


@dataclasses.dataclass(frozen=True)
class Seq2SeqConfig(GPTConfig):
    """GPTConfig plus the encoder/decoder split.

    ``n_layers`` is the DECODER depth (so LM-tuned knobs like remat policy
    and FLOPs accounting carry over); ``enc_layers`` sizes the encoder
    (default: same depth).  ``src_seq_len`` bounds the source length for
    learned positions and the memory cache (default: ``seq_len``).
    """

    enc_layers: Optional[int] = None
    src_seq_len: Optional[int] = None

    @property
    def encoder_layers(self) -> int:
        return self.enc_layers if self.enc_layers is not None else self.n_layers

    @property
    def source_len(self) -> int:
        return self.src_seq_len if self.src_seq_len is not None else self.seq_len


@struct.dataclass
class Seq2SeqBatch:
    """Source tokens + teacher-forced decoder tokens/targets.

    ``src_mask`` flags real source positions (False = padding: masked out of
    every cross-attention); ``loss_mask`` zeroes padding out of the CE.
    """

    src_tokens: jax.Array  # [B, S_src]
    tokens: jax.Array  # [B, S_dst] decoder input (BOS-shifted)
    targets: jax.Array  # [B, S_dst]
    src_mask: Optional[jax.Array] = None  # [B, S_src] bool/0-1
    loss_mask: Optional[jax.Array] = None  # [B, S_dst]

    @property
    def size(self) -> int:
        return self.src_tokens.shape[0]


def cross_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    memory_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Full-visibility attention of decoder queries over encoder memory.

    ``q``: [B, T, h, dh]; ``k``/``v``: [B, S, h_kv, dh] with
    ``h % h_kv == 0`` — grouped queries contract against their kv head
    directly (GQA-native, no K/V expansion).  ``memory_mask`` [B, S] masks
    source padding.  fp32 softmax, bf16 einsums on the MXU.
    """
    b, tq, h, head_dim = q.shape
    h_kv = k.shape[2]
    group = h // h_kv
    scale = 1.0 / jnp.sqrt(head_dim).astype(q.dtype)
    qg = (q * scale).reshape(b, tq, h_kv, group, head_dim)
    scores = jnp.einsum("bqngd,bknd->bngqk", qg, k).astype(jnp.float32)
    if memory_mask is not None:
        keep = memory_mask.astype(bool)[:, None, None, None, :]
        scores = jnp.where(keep, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, v)
    return out.reshape(b, tq, h, head_dim)


class CrossAttention(nn.Module):
    """Decoder-side cross-attention into the encoder memory, TP-structural.

    Q is column-parallel at query-head width; the memory K/V projection is
    column-parallel at kv-head width; the output closes the Megatron pair
    row-parallel.  With ``decode=True`` and ``memory`` given (prefill), the
    projected K/V are written to a ``cache`` collection; subsequent steps
    pass ``memory=None`` and read the cache — the source is projected
    exactly once per generation.
    """

    config: Seq2SeqConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        memory: Optional[jax.Array],
        memory_mask: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
    ) -> jax.Array:
        cfg = self.config
        tp_size = axis_size_or_none(cfg.model_axis) or 1
        n_kv = cfg.n_kv_heads or cfg.n_heads
        local_heads = cfg.n_heads // tp_size
        local_kv = n_kv // tp_size

        q = TPDense(
            features=cfg.n_heads * cfg.head_dim,
            axis_name=cfg.model_axis,
            style="column",
            use_bias=cfg.dense_bias,
            dtype=cfg.dtype,
            name="q",
        )(x)
        q = q.reshape(*x.shape[:-1], local_heads, cfg.head_dim)

        if memory is not None:
            kv = TPDense(
                features=2 * n_kv * cfg.head_dim,
                axis_name=cfg.model_axis,
                style="column",
                use_bias=cfg.dense_bias,
                dtype=cfg.dtype,
                name="kv",
            )(memory)
            kv = kv.reshape(*memory.shape[:-1], local_kv, 2 * cfg.head_dim)
            k, v = jnp.split(kv, 2, axis=-1)
        elif not decode:
            raise ValueError("cross-attention needs `memory` outside decode")
        else:
            k = v = None  # read from cache below

        if decode:
            b = x.shape[0]
            s_src = memory.shape[1] if memory is not None else None
            if k is None and not self.has_variable("cache", "cross_key"):
                raise ValueError(
                    "decode step before prefill: run one decode=True apply "
                    "WITH `memory` first to populate the cross K/V cache"
                )
            init_shape = (b, s_src or 1, local_kv, cfg.head_dim)
            cached_k = self.variable(
                "cache", "cross_key", jnp.zeros, init_shape, cfg.dtype
            )
            cached_v = self.variable(
                "cache", "cross_value", jnp.zeros, init_shape, cfg.dtype
            )
            cached_m = self.variable(
                "cache",
                "cross_mask",
                jnp.ones,
                (b, s_src or 1),
                jnp.bool_,
            )
            if k is not None:  # prefill: project once, persist
                cached_k.value = k
                cached_v.value = v
                if memory_mask is not None:
                    cached_m.value = memory_mask.astype(bool)
            k, v = cached_k.value, cached_v.value
            memory_mask = cached_m.value

        out = cross_attention(q, k, v, memory_mask)
        out = out.reshape(*x.shape[:-1], local_heads * cfg.head_dim)
        out = TPDense(
            features=cfg.d_model,
            axis_name=cfg.model_axis,
            style="row",
            use_bias=cfg.dense_bias,
            dtype=cfg.dtype,
            name="out",
        )(out)
        if cfg.dropout_rate > 0.0:
            out = nn.Dropout(rate=cfg.dropout_rate, deterministic=not train)(out)
        return out


class DecoderBlock(nn.Module):
    """Pre-norm decoder block: causal self-attn, cross-attn, MLP."""

    config: Seq2SeqConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        memory: Optional[jax.Array],
        memory_mask: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        attn_bias: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        h = make_norm(cfg, "norm_self")(x).astype(cfg.dtype)
        x = x + Attention(cfg, name="self_attn")(
            h, positions=positions, train=train, decode=decode,
            attn_bias=attn_bias,
        )
        h = make_norm(cfg, "norm_cross")(x).astype(cfg.dtype)
        x = x + CrossAttention(cfg, name="cross_attn")(
            h, memory, memory_mask=memory_mask, train=train, decode=decode
        )
        h = make_norm(cfg, "norm_mlp")(x).astype(cfg.dtype)
        if cfg.moe_experts > 0:
            if decode and cfg.moe_router == "expert_choice":
                # Block's guard, mirrored: a single-token decode step
                # collapses the EC routing pool to one token per row
                raise NotImplementedError(
                    "incremental decoding with expert-choice routing "
                    "(the routing pool collapses to one token per row)"
                )
            from tpu_parallel.models.moe import MoEMLP

            x = x + MoEMLP(cfg, name="moe")(h, train=train)
        else:
            x = x + MLP(cfg, name="mlp")(h, train=train)
        return x


class _ScanDecoderBlock(nn.Module):
    """nn.scan target for the decoder stack: memory rides the carry."""

    config: Seq2SeqConfig
    train: bool
    decode: bool = False
    block_cls: type = DecoderBlock

    @nn.compact
    def __call__(self, carry, _):
        x, memory, memory_mask, positions, attn_bias = carry
        x = self.block_cls(self.config, name="block")(
            x,
            memory,
            memory_mask=memory_mask,
            positions=positions,
            train=self.train,
            decode=self.decode,
            attn_bias=attn_bias,
        )
        return (x, memory, memory_mask, positions, attn_bias), None


class DecoderStack(nn.Module):
    """``n_layers`` decoder blocks, scanned+remat'd like BlockStack."""

    config: Seq2SeqConfig
    n_layers: int

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        memory: Optional[jax.Array],
        memory_mask: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        attn_bias: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        remat_kwargs = remat_kwargs_for(cfg)
        base_block = fsdp.maybe_shard(DecoderBlock, cfg)
        if cfg.scan_layers:
            if seq_parallel_active(cfg):
                # seq-parallel attention output is seq-varying; the scan
                # carry must enter seq-varying too (see BlockStack)
                from tpu_parallel.core.metrics import pvary_missing, vma_of

                x = pvary_missing(x, vma_of(lax.axis_index(cfg.seq_axis)))
            scan_target = _ScanDecoderBlock
            if cfg.remat and not decode:
                scan_target = nn.remat(_ScanDecoderBlock, **remat_kwargs)
            # None slots (decode steps read memory from the per-layer cache)
            # pass through the carry as empty pytree nodes — structure
            # stays static across prefill and steps
            stacked = nn.scan(
                scan_target,
                variable_axes={"params": 0, "cache": 0, "losses": 0},
                variable_broadcast=False,
                split_rngs={"params": True, "dropout": True},
                length=self.n_layers,
                unroll=cfg.scan_unroll,
                metadata_params={nn.PARTITION_NAME: None},
            )(cfg, train, decode, base_block, name="layers")
            (x, _, _, _, _), _ = stacked(
                (x, memory, memory_mask, positions, attn_bias), None
            )
        else:
            block_cls = (
                nn.remat(base_block, static_argnums=(5, 6), **remat_kwargs)
                if cfg.remat and not decode
                else base_block
            )
            for i in range(self.n_layers):
                x = block_cls(cfg, name=f"layer_{i}")(
                    x, memory, memory_mask, positions, train, decode, attn_bias
                )
        return x


class _DecodePos(nn.Module):
    """Model-level decode position counter (compact, so the cache variable
    can be created lazily on the first mutable decode apply — mirrors
    GPTLM's in-line counter, which a setup-style method may not create)."""

    @nn.compact
    def __call__(self, dst: jax.Array) -> jax.Array:
        counter = self.variable(
            "cache", "decode_pos", lambda: jnp.zeros((), jnp.int32)
        )
        positions = jnp.broadcast_to(
            counter.value + jnp.arange(dst.shape[1])[None, :], dst.shape
        )
        counter.value = counter.value + dst.shape[1]
        return positions


class EncoderDecoder(nn.Module):
    """``(src [B, S_src], dst [B, S_dst]) -> logits [B, S_dst, vocab]``.

    The token embedding is shared between encoder input, decoder input
    (T5-style tying); the lm_head stays untied like the LM family.  The
    encoder runs the existing :class:`BlockStack` with
    ``bidirectional=True``; the decoder is :class:`DecoderStack`.

    ``positions`` contract under ``positional="relative"``: every row must
    hold the SAME position vector (the per-stack bias tables are computed
    once from row 0; ragged/packed per-row positions are refused by the
    framework entry points — a direct ``apply`` with per-row positions would
    silently get row-0 bias for all rows).
    """

    config: Seq2SeqConfig

    def setup(self):
        cfg = self.config
        if cfg.pipe_interleave > 1 and cfg.pipe_size <= 1:
            raise ValueError(
                "pipe_interleave > 1 requires pipe_size > 1 (a pipe mesh "
                "axis); on a pipe=1 mesh the knob would be silently ignored"
            )
        if cfg.moe_experts > 0 and cfg.pipe_size > 1:
            raise NotImplementedError(
                "MoE under the pipelined encoder-decoder (bubble-tick sow "
                "masking is wired for the GPTLM pipeline only)"
            )
        if not cfg.prenorm or cfg.embed_norm:
            # Block honors prenorm but DecoderBlock and the enc/dec final
            # norms are pre-norm-shaped — a half-applied knob would build a
            # chimera silently
            raise NotImplementedError(
                "post-norm / embed-norm variants in the seq2seq stacks "
                "(BERT-interop knobs; the seq2seq family is pre-norm)"
            )
        # encoder sees bidirectional attention; decoder causal.  Positions
        # are bounded by the LONGER of the two lengths so the shared learned
        # table covers both sides.
        table = max(cfg.seq_len, cfg.source_len)
        self._enc_cfg = dataclasses.replace(
            cfg, bidirectional=True, seq_len=cfg.source_len
        )
        self._dec_cfg = dataclasses.replace(cfg, bidirectional=False)
        self.embed = fsdp.maybe_shard(Embedding, cfg)(
            dataclasses.replace(cfg, seq_len=table), name="embed"
        )
        if cfg.pipe_size > 1:
            # Heterogeneous stages, homogeneous ranks: each pipe rank owns
            # encoder_layers/pipe encoder blocks AND n_layers/pipe decoder
            # blocks, run as two sequential GPipe passes.  The encoder
            # pipeline broadcasts its output (one d_model all-reduce) so
            # every rank holds the memory; the decoder pipeline then feeds
            # it to every stage's cross-attention as a per-microbatch extra
            # — model input already replicated per rank, zero ring traffic.
            import functools

            from tpu_parallel.parallel import pp

            if cfg.positional == "relative":
                raise NotImplementedError(
                    "relative position bias under pipeline parallelism"
                )
            if cfg.pipe_interleave > 1:
                raise NotImplementedError(
                    "the interleaved schedule for encoder-decoder models"
                )
            for n, what in (
                (cfg.encoder_layers, "enc_layers"),
                (cfg.n_layers, "n_layers"),
            ):
                if n % cfg.pipe_size != 0:
                    raise ValueError(
                        f"{what}={n} not divisible by pipe_size={cfg.pipe_size}"
                    )
            self.encoder = pp.PipelineModule(
                stage_fn=functools.partial(
                    BlockStack,
                    self._enc_cfg,
                    cfg.encoder_layers // cfg.pipe_size,
                ),
                num_microbatches=cfg.num_microbatches,
                axis_name=cfg.pipe_axis,
                broadcast_outputs=True,
                name="encoder",
            )
            self.decoder = pp.PipelineModule(
                stage_fn=functools.partial(
                    DecoderStack, self._dec_cfg, cfg.n_layers // cfg.pipe_size
                ),
                num_microbatches=cfg.num_microbatches,
                axis_name=cfg.pipe_axis,
                name="decoder",
            )
        else:
            self.encoder = BlockStack(
                self._enc_cfg, cfg.encoder_layers, name="encoder"
            )
            self.decoder = DecoderStack(
                self._dec_cfg, cfg.n_layers, name="decoder"
            )
        self.enc_norm = make_norm(cfg, "enc_norm")
        self.dec_norm = make_norm(cfg, "dec_norm")
        self.lm_head = _make_lm_head(cfg)
        self.decode_pos = _DecodePos(name="pos_counter")
        self.enc_rel_bias = self.dec_rel_bias = None
        if cfg.positional == "relative":
            # T5: each stack shares ONE bucketed bias table across its
            # layers (bidirectional buckets for the encoder, causal for the
            # decoder); cross-attention carries no bias
            if cfg.attn_impl != "xla":
                raise NotImplementedError(
                    "relative position bias needs attn_impl='xla'"
                )
            self.enc_rel_bias = RelativePositionBias(
                self._enc_cfg, bidirectional=True, name="enc_rel_bias"
            )
            self.dec_rel_bias = RelativePositionBias(
                self._dec_cfg, bidirectional=False, name="dec_rel_bias"
            )

    def encode(
        self,
        src: jax.Array,
        src_mask: Optional[jax.Array] = None,
        train: bool = True,
    ) -> jax.Array:
        """Source tokens -> memory [B, S_src, d_model].

        Padding is excluded from encoder self-attention via segment_ids
        (pad positions form their own segment), and from every
        cross-attention via the mask the caller threads through.
        """
        x = self.embed(src)  # Embedding offsets positions under SP itself
        segment_ids = None
        if src_mask is not None:
            # real tokens segment 1, padding segment 0 — same-segment
            # visibility keeps padding out of the real tokens' softmax
            segment_ids = src_mask.astype(jnp.int32)
        attn_bias = None
        if self.enc_rel_bias is not None:
            pos = jnp.arange(src.shape[1])
            attn_bias = self.enc_rel_bias(pos, pos)
        if self.config.pipe_size > 1:
            extras = (
                {"segment_ids": segment_ids} if segment_ids is not None else None
            )
            x = self.encoder(x, train=train, extras=extras)
        else:
            x = self.encoder(
                x, segment_ids=segment_ids, train=train, attn_bias=attn_bias
            )
        return self.enc_norm(x).astype(self.config.dtype)

    def decode(
        self,
        dst: jax.Array,
        memory: Optional[jax.Array],
        src_mask: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        hidden_only: bool = False,
    ) -> jax.Array:
        cfg = self.config
        if decode and seq_parallel_active(cfg):
            # generation shards nothing over seq (the batch arrives
            # replicated on that axis); running the SP offsets/gathers on a
            # bound seq axis would silently corrupt positions and memory
            raise NotImplementedError(
                "incremental decoding under sequence parallelism "
                "(serve seq2seq on a data/model mesh)"
            )
        if decode and positions is None:
            positions = self.decode_pos(dst)
        if memory is not None and seq_parallel_active(cfg):
            # the memory arrives seq-SHARDED (the encoder ran under SP);
            # every decoder layer's cross-attention needs the whole source.
            # ONE d_model-wide gather here — outside the remat'd stack, so
            # it is neither repeated per layer nor replayed in the backward
            memory = lax.all_gather(memory, cfg.seq_axis, axis=1, tiled=True)
            if src_mask is not None:
                src_mask = lax.all_gather(
                    src_mask, cfg.seq_axis, axis=1, tiled=True
                )
        x = self.embed(dst, positions=positions)
        attn_bias = None
        if self.dec_rel_bias is not None:
            attn_bias = self.dec_rel_bias.for_step(
                positions, dst.shape[1], cfg.seq_len, decode
            )
        if cfg.pipe_size > 1:
            if decode:
                raise NotImplementedError(
                    "incremental decoding for pipelined encoder-decoder "
                    "models (the cross-attention caches would need their "
                    "own ring plumbing)"
                )
            # memory/mask are model inputs every rank holds (the encoder
            # pipeline broadcast its output): ride as per-microbatch extras
            extras = {"memory": memory}
            if src_mask is not None:
                extras["memory_mask"] = src_mask
            if positions is not None:
                extras["positions"] = positions
            x = self.decoder(x, train=train, extras=extras)
        else:
            x = self.decoder(
                x,
                memory,
                memory_mask=src_mask,
                positions=positions,
                train=train,
                decode=decode,
                attn_bias=attn_bias,
            )
        x = self.dec_norm(x).astype(cfg.dtype)
        if hidden_only:
            return x
        return self.lm_head(x)

    def __call__(
        self,
        src: jax.Array,
        dst: jax.Array,
        src_mask: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        hidden_only: bool = False,
    ) -> jax.Array:
        memory = self.encode(src, src_mask=src_mask, train=train)
        return self.decode(
            dst,
            memory,
            src_mask=src_mask,
            train=train,
            decode=decode,
            hidden_only=hidden_only,
        )


def make_seq2seq_loss(config: Seq2SeqConfig, train: bool = True):
    """Teacher-forced CE over decoder positions, TP/FSDP-aware.

    Same contract as :func:`make_gpt_loss` (``accumulate_gradients`` loss
    shape); the CE machinery is shared (:func:`make_ce_fn` — vocab-parallel
    under TP, chunked under ``loss_chunk``, pre-gathered lm_head).
    """
    fold_axes = (
        config.data_axis, config.model_axis, config.pipe_axis, config.seq_axis
    )
    ce_fn = make_ce_fn(config)

    def loss_fn(params, apply_fn, batch: Seq2SeqBatch, rng):
        dropout_rng = fold_rng_over_axis(rng, fold_axes)
        apply_kwargs = dict(
            src_mask=batch.src_mask,
            train=train,
            hidden_only=True,
            rngs={"dropout": dropout_rng},
        )
        aux_loss = 0.0
        if config.moe_experts > 0:
            hidden, mods = apply_fn(
                {"params": params},
                batch.src_tokens,
                batch.tokens,
                mutable=["losses"],
                **apply_kwargs,
            )
            sown = jax.tree_util.tree_leaves(mods.get("losses", {}))
            if sown:
                # every encoder AND decoder block sows once per apply (PP
                # is refused with MoE, so no microbatch factor)
                denom = config.encoder_layers + config.n_layers
                aux_loss = sum(jnp.sum(leaf) for leaf in sown) / denom
        else:
            hidden = apply_fn(
                {"params": params},
                batch.src_tokens,
                batch.tokens,
                **apply_kwargs,
            )
        mask = (
            batch.loss_mask
            if batch.loss_mask is not None
            else jnp.ones(batch.targets.shape, jnp.float32)
        )
        if config.pipe_size > 1:
            # real logits live on the last pipe rank only
            from tpu_parallel.parallel import pp

            mask = mask * pp.last_stage_mask(config.pipe_axis)
        n_tok = mask.sum()
        loss_sum, correct = ce_fn(
            _lm_head_params(config, params), hidden, batch.targets, mask
        )
        metrics: Metrics = {
            "loss": (loss_sum, n_tok),
            "accuracy": (correct.astype(jnp.float32), n_tok),
        }
        total = loss_sum / jnp.maximum(n_tok, 1.0)
        if config.moe_experts > 0:
            metrics["moe_balance"] = (aux_loss * n_tok, n_tok)
            total = total + config.moe_balance_weight * aux_loss
        return total, metrics

    return loss_fn


def seq2seq_generate(
    model: EncoderDecoder,
    params,
    src: jax.Array,
    src_mask: Optional[jax.Array] = None,
    rng: Optional[jax.Array] = None,
    *,
    bos_id: int = 0,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
) -> jax.Array:
    """Encode once, then KV-cached autoregressive decoding.

    Returns [B, max_new_tokens].  Greedy at ``temperature == 0``; the
    sampling filters are shared with the LM path
    (:func:`~tpu_parallel.models.generate._sample`).  Single-device params
    layout — for mesh-sharded states use :func:`seq2seq_generate_sharded`
    (or ``export_single_device_params`` for DP/FSDP-only meshes).
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _seq2seq_generate_jit(
        model,
        params,
        src,
        src_mask,
        rng,
        bos_id=bos_id,
        max_new_tokens=max_new_tokens,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
    )


@functools.partial(
    jax.jit,
    static_argnums=(0,),
    static_argnames=("bos_id", "max_new_tokens", "temperature", "top_k", "top_p"),
)
def _seq2seq_generate_jit(
    model: EncoderDecoder,
    params,
    src,
    src_mask,
    rng,
    *,
    bos_id: int,
    max_new_tokens: int,
    temperature: float,
    top_k: int,
    top_p: float = 0.0,
):
    """Module-level jitted core: a serving loop pays trace + compile once per
    (model, shapes, knobs), not per call."""
    return _seq2seq_core(
        model, params, src, src_mask, rng,
        bos_id=bos_id, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p,
    )


def _seq2seq_core(
    model: EncoderDecoder,
    params,
    src,
    src_mask,
    rng,
    *,
    bos_id: int,
    max_new_tokens: int,
    temperature: float,
    top_k: int,
    top_p: float = 0.0,
):
    """Traceable encode + prefill + decode scan, shared by the jit path and
    the shard_map path (:func:`seq2seq_generate_sharded`).  Under a bound
    model axis the lm_head logits stay vocab-sharded and sampling runs
    vocab-parallel (every TP rank emits the same token).

    The length guards live HERE (trace time, static shapes) so BOTH entry
    points enforce them: nn.Embed clamps out-of-range position indices
    under jit and dynamic_update_slice clamps cache overflow — either
    would silently corrupt generations instead of failing."""
    from tpu_parallel.models.generate import _sample, _sample_sharded

    cfg = model.config
    if max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) exceeds decoder seq_len "
            f"({cfg.seq_len})"
        )
    if src.shape[1] > cfg.source_len:
        raise ValueError(
            f"source length ({src.shape[1]}) exceeds the encoder's "
            f"source_len ({cfg.source_len})"
        )
    b = src.shape[0]
    memory = model.apply(
        {"params": params}, src, src_mask, False, method=model.encode
    )
    head = _make_lm_head(cfg, name=None, gather=False, fsdp_wrap=False)
    lm_params = _lm_head_params(cfg, params)

    def next_token(h, rng):
        logits = head.apply({"params": lm_params}, h[:, -1:])[:, 0]
        if axis_size_or_none(cfg.model_axis) is not None:
            return _sample_sharded(
                logits, rng, temperature, top_k, top_p, cfg.model_axis
            )
        return _sample(logits, rng, temperature, top_k, top_p)

    # prefill: BOS through the decoder populates self- and cross-caches
    bos = jnp.full((b, 1), bos_id, jnp.int32)
    hidden, variables = model.apply(
        {"params": params},
        bos,
        memory,
        src_mask,
        None,
        False,
        True,
        True,
        method=model.decode,
        mutable=["cache"],
    )
    rng, sub = jax.random.split(rng)
    first = next_token(hidden, sub)

    def step(carry, _):
        cache, tok, rng = carry
        hidden, updated = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            None,
            None,
            None,
            False,
            True,
            True,
            method=model.decode,
            mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        nxt = next_token(hidden, sub)
        return (updated["cache"], nxt, rng), tok

    init = (variables["cache"], first, rng)
    (_, last, _), toks = lax.scan(step, init, None, length=max_new_tokens - 1)
    return jnp.concatenate([toks.T, last[:, None]], axis=1)


def seq2seq_generate_sharded(
    model: EncoderDecoder,
    params,
    src: jax.Array,
    mesh,
    src_mask: Optional[jax.Array] = None,
    rng: Optional[jax.Array] = None,
    *,
    bos_id: int = 0,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    param_specs=None,
    batch_spec=None,
) -> jax.Array:
    """Serve a mesh-trained seq2seq state under its own mesh.

    Same contract as :func:`~tpu_parallel.models.generate.generate_sharded`:
    TP-split weights stay split (the KV and cross-memory caches shard over
    heads exactly as activations), each data shard decodes its rows, and
    sampling under TP runs vocab-parallel so every model rank emits the
    same token.  Sampling RNG folds over the data axis only.
    """
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.models.generate import _HashableTree

    if param_specs is None:
        param_specs = nn.get_partition_spec(params)
    if batch_spec is None:
        batch_spec = P(model.config.data_axis)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    # the shard_map arity is fixed, so a placeholder all-ones mask always
    # rides along; has_mask keeps the no-mask call on the unmasked fast path
    # inside the core (an all-ones mask is semantically identical but pays
    # the segment-ids masking compute in encode on every call)
    has_mask = src_mask is not None
    if src_mask is None:
        src_mask = jnp.ones(src.shape, jnp.bool_)
    fn = _sharded_seq2seq_fn(
        model,
        mesh,
        _HashableTree.of(param_specs),
        batch_spec,
        bos_id,
        max_new_tokens,
        temperature,
        top_k,
        top_p,
        has_mask,
    )
    return fn(params, src, src_mask, rng)


@functools.lru_cache(maxsize=32)
def _sharded_seq2seq_fn(
    model, mesh, specs, batch_spec, bos_id, max_new_tokens, temperature, top_k,
    top_p=0.0, has_mask=True,
):
    from tpu_parallel.models.generate import build_sharded_serving

    def core(model_, params, src, src_mask, rng):
        return _seq2seq_core(
            model_, params, src, src_mask if has_mask else None, rng,
            bos_id=bos_id, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
        )

    return build_sharded_serving(
        model, mesh, specs.tree(), (batch_spec, batch_spec), batch_spec, core
    )


def t5_small(**overrides) -> Seq2SeqConfig:
    """T5-small-shaped encoder-decoder (~60M params, vocab padded to 128)."""
    return Seq2SeqConfig(
        **{
            **dict(
                vocab_size=32128,
                d_model=512,
                n_layers=6,
                enc_layers=6,
                n_heads=8,
                seq_len=512,
                mlp_ratio=4,
                norm="rmsnorm",
                mlp="gelu",
            ),
            **overrides,
        }
    )


def t5_small_hf(**overrides) -> Seq2SeqConfig:
    """T5-small in its checkpoint-faithful form, for
    :func:`~tpu_parallel.models.hf.from_hf_t5`: relative position bias
    (32 buckets / max distance 128, one table per stack), T5LayerNorm
    (= RMSNorm, eps 1e-6), bias-free denses, ReLU MLP (pass
    ``mlp="geglu"`` for v1.1 checkpoints), unscaled attention folded into
    the imported q kernels.  xla attention path (the bias refuses the
    flash kernels); for from-scratch TPU training prefer :func:`t5_small`.
    """
    return Seq2SeqConfig(
        **{
            **dict(
                vocab_size=32128,
                d_model=512,
                n_layers=6,
                enc_layers=6,
                n_heads=8,
                seq_len=512,
                mlp_ratio=4,
                positional="relative",
                norm="rmsnorm",
                norm_eps=1e-6,
                mlp="relu",
                dense_bias=False,
                attn_impl="xla",
                scan_layers=False,
            ),
            **overrides,
        }
    )


def tiny_seq2seq(**overrides) -> Seq2SeqConfig:
    """Toy config for CPU-mesh tests."""
    return Seq2SeqConfig(
        **{
            **dict(
                vocab_size=256,
                d_model=32,
                n_layers=2,
                enc_layers=2,
                n_heads=4,
                seq_len=32,
                src_seq_len=32,
                dtype=jnp.float32,
                num_microbatches=2,
            ),
            **overrides,
        }
    )


@functools.partial(
    jax.jit, static_argnums=(0,),
    static_argnames=(
        "bos_id", "max_new_tokens", "num_beams", "length_penalty", "lazy",
    ),
)
def seq2seq_generate_beam(
    model: EncoderDecoder,
    params,
    src: jax.Array,
    src_mask: Optional[jax.Array] = None,
    *,
    bos_id: int = 0,
    max_new_tokens: int = 32,
    num_beams: int = 4,
    length_penalty: float = 0.0,
    lazy: bool = True,
):
    """Beam-search decoding for the encoder-decoder family.

    Returns ``(tokens [batch, max_new_tokens], scores [batch])`` — the
    highest-scoring continuation per source row, scores = total
    log-probability / ``len**length_penalty``.  Same mechanics as the LM
    :func:`~tpu_parallel.models.generate.generate_beam`: encode + prefill
    ONCE per source row, replicate the caches ``num_beams`` ways (beams
    are identical until the first expansion), then per step take the top
    beams of the joint continuations.  ``lazy=True`` (default) follows
    beam ancestry through per-slot source-row tables (self-attention
    caches are never re-gathered; cross caches are beam-invariant either
    way); ``lazy=False`` physically reorders the self K/V and position
    rows every step.  Fixed-length decoding (no EOS early exit),
    single-device params layout.
    """
    import dataclasses

    cfg = model.config
    b = src.shape[0]
    if max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) exceeds decoder seq_len "
            f"({cfg.seq_len})"
        )
    if src.shape[1] > cfg.source_len:
        raise ValueError(
            f"source length ({src.shape[1]}) exceeds the encoder's "
            f"source_len ({cfg.source_len})"
        )
    k = num_beams
    vocab = cfg.vocab_size
    memory = model.apply(
        {"params": params}, src, src_mask, False, method=model.encode
    )
    head = _make_lm_head(cfg, name=None, gather=False, fsdp_wrap=False)
    lm_params = _lm_head_params(cfg, params)
    logp_of = lambda h: jax.nn.log_softmax(
        head.apply({"params": lm_params}, h[:, -1:])[:, 0].astype(jnp.float32)
    )

    from tpu_parallel.models.generate import (
        beam_advance_src,
        beam_backtrack,
        beam_expand_cache,
        beam_reorder_cache,
        beam_seed_src,
    )

    # prefill always runs the plain (beam_width=0) model: rows are still
    # un-expanded source rows (same guard as the LM generate_beam)
    plain = (
        model
        if cfg.beam_width == 0
        else type(model)(dataclasses.replace(cfg, beam_width=0))
    )
    bos = jnp.full((b, 1), bos_id, jnp.int32)
    hidden, variables = plain.apply(
        {"params": params}, bos, memory, src_mask, None, False, True, True,
        method=plain.decode, mutable=["cache"],
    )
    cache0 = beam_expand_cache(variables["cache"], k)
    scores, first = jax.lax.top_k(logp_of(hidden), k)  # [b, k] each
    tok = first.reshape(b * k).astype(jnp.int32)

    if lazy:
        stepper = type(model)(dataclasses.replace(cfg, beam_width=k))
        cache0 = beam_seed_src(cache0, k)
    else:
        stepper = plain

    def step(carry, _):
        cache, tok, scores = carry
        hidden, updated = stepper.apply(
            {"params": params, "cache": cache},
            tok[:, None], None, None, None, False, True, True,
            method=stepper.decode, mutable=["cache"],
        )
        joint = scores[:, :, None] + logp_of(hidden).reshape(b, k, vocab)
        new_scores, flat_idx = jax.lax.top_k(joint.reshape(b, k * vocab), k)
        src_beam = flat_idx // vocab
        next_tok = (flat_idx % vocab).astype(jnp.int32)
        row_idx = (src_beam + jnp.arange(b)[:, None] * k).reshape(b * k)
        if lazy:
            # self-attention ancestry rides the tiny int32 tables; cross
            # caches are beam-invariant and untouched either way
            cache = beam_advance_src(updated["cache"], row_idx)
        else:
            # cross caches are beam-INVARIANT (written once at prefill;
            # every beam of a row holds identical copies) — skip their
            # per-step gather, it would move n_layers full source caches
            # for a no-op
            cache = beam_reorder_cache(
                updated["cache"], row_idx,
                skip_prefixes=("cross_key", "cross_value", "cross_mask"),
            )
        return (
            (cache, next_tok.reshape(b * k), new_scores),
            (next_tok, src_beam),
        )

    init = (cache0, tok, scores)
    (_, _, scores), (toks, src_beams) = lax.scan(
        step, init, None, length=max_new_tokens - 1
    )

    out = beam_backtrack(first, toks, src_beams, scores)
    best_scores = jnp.max(scores, axis=-1)
    if length_penalty:
        best_scores = best_scores / (
            jnp.float32(max_new_tokens) ** length_penalty
        )
    return out.astype(jnp.int32), best_scores
