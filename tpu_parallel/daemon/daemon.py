"""The durable serving daemon: a long-lived wall-clock process around
the cluster :class:`~tpu_parallel.cluster.frontend.Frontend`.

Everything below this layer runs on the injectable clock and is soaked
deterministically by the chaos/swap/autopilot harnesses; this module is
the thin shell that finally lets it SERVE — and makes accepted work
survive the process itself:

- **Write-ahead journal** (``daemon/journal.py``): every accepted
  submission is journaled and fsynced BEFORE the accept is returned;
  delivered tokens and terminal events follow with per-tick batched
  fsync.  A ``kill -9`` mid-stream followed by a restart on the same
  journal path REPLAYS the log: finished requests become idempotent
  dedupe-token responses, accepted-but-unfinished requests re-admit
  with their durable token prefix forced (the cluster's own
  forced-prefix machinery), so greedy streams continue bitwise and no
  acknowledged request is ever lost or completed twice.
- **Signal layer**: SIGTERM begins a graceful drain (in-flight work
  finishes, new submissions are refused typed ``draining``, exit 0
  within ``grace_seconds``); a second SIGTERM — or a blown grace
  window — forces a fast shutdown with the journal as the recovery
  contract for whatever was still open (exit 1).  SIGHUP re-reads
  ``reload_path`` and rolls new weights through the PR 10 swap path.
- **Clock discipline**: the daemon owns the ONE
  :class:`~tpu_parallel.daemon.wallclock.WallClock` and injects it into
  the frontend, so per-request wall-clock deadlines ride the exact same
  deadline machinery the fake-clock tests pin.  Handing the constructor
  a fake clock instead makes the entire daemon — journal, recovery,
  drain, dedupe — a deterministic unit-test subject
  (``tests/test_daemon.py`` crash-replays it in-process).

Threading: the tick pump (``run()``) and the HTTP handler threads
(``daemon/http.py``) serialize on one RLock; per-request streaming
rides lock-free subscriber queues fed from inside the tick.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import signal as _signal
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from tpu_parallel.daemon import iofaults
from tpu_parallel.daemon.journal import (
    REC_DECISION,
    REC_RECOVERY,
    REC_SHUTDOWN,
    REC_SUBMIT,
    REC_TERMINAL,
    REC_TOKENS,
    JournalWriter,
    drop_torn_tail,
    load_state,
)
from tpu_parallel.daemon.wallclock import WallClock
from tpu_parallel.obs.spool import read_span_log
from tpu_parallel.obs.tracer import NULL_TRACER, TraceContext
from tpu_parallel.serving.request import (
    FINISHED,
    QUEUED,
    REJECTED,
    RUNNING,
    Request,
    SamplingParams,
    StreamEvent,
)

DAEMON_TRACK = "daemon"  # tracer track for signals/recovery/shutdown

# exit codes (the signal contract; docs/13_daemon.md)
EXIT_CLEAN = 0  # drained: every accepted request terminal, journal clean
EXIT_FORCED = 1  # fast shutdown: open work recovers from the journal

# typed degraded-mode rejection reasons (HTTP maps both to 503: the
# balancer should route elsewhere, the client should retry elsewhere)
REJECT_DEGRADED = "degraded"  # persistent journal failure: no new accepts
REJECT_JOURNAL = "journal_error"  # THIS accept could not be made durable


@dataclasses.dataclass(frozen=True)
class DaemonConfig:
    """Daemon shell knobs.

    - ``grace_seconds``: the SIGTERM drain window — in-flight work that
      outlives it is abandoned to the journal (fast shutdown, exit 1).
    - ``idle_sleep_seconds``: tick-pump sleep while the frontend has no
      work (busy ticks never sleep).
    - ``fsync_batch``: journal records per disk barrier (submissions and
      shutdown records always sync immediately).
    - ``reload_path``: SIGHUP reads this JSON file
      (``{"checkpoint_dir": ..., "step": ...}``) and rolls the weights
      through ``Frontend.begin_swap`` — the PR 10 canary/rollback
      machinery, not a blind rebind.  None = SIGHUP is a counted no-op.
    - ``completed_retention``: terminal records (and their dedupe
      tokens) kept in memory for idempotent replies, oldest-evicted
      beyond it — the daemon's memory stays bounded at any uptime.
      The retained horizon survives compaction; beyond it only the
      in-RAM dedupe horizon ends.
    - ``compact_interval_records``: once this many records have
      appended since the last rotation, the journal COMPACTS — open
      state snapshots into a fresh segment, retired records drop, so
      restart replay reads O(open + retained) records instead of
      O(lifetime).  0 disables rotation (the PR 14 unbounded-file
      behavior).
    - ``degrade_after_io_errors``: consecutive journal append/fsync
      failures before the daemon enters DEGRADED mode — new
      submissions refuse typed ``degraded`` (503), in-flight work
      drains, ``/healthz`` flips 503 with the reason, and the process
      stays up for its balancer instead of dying mid-accept.
    - ``role``: the daemon's fleet role (``prefill`` / ``decode`` /
      ``mixed`` — :mod:`tpu_parallel.fleet.roles`), advertised on
      ``/healthz``.  A ``decode``-role daemon typed-refuses fresh
      client submissions (reason ``role``, 503 — a routing refusal,
      not failure evidence) and accepts only the router's handoff
      continuations; ``prefill`` and ``mixed`` accept everything
      (colocated decode is the disaggregation fallback).
    """

    grace_seconds: float = 30.0
    idle_sleep_seconds: float = 0.005
    fsync_batch: int = 32
    reload_path: Optional[str] = None
    completed_retention: int = 50_000
    compact_interval_records: int = 4096
    degrade_after_io_errors: int = 3
    role: str = "mixed"

    def __post_init__(self):
        from tpu_parallel.fleet.roles import validate_role

        validate_role(self.role)
        if self.grace_seconds <= 0:
            raise ValueError(f"grace_seconds={self.grace_seconds} <= 0")
        if self.fsync_batch < 1:
            raise ValueError(f"fsync_batch={self.fsync_batch} < 1")
        if self.completed_retention < 1:
            raise ValueError(
                f"completed_retention={self.completed_retention} < 1"
            )
        if self.compact_interval_records < 0:
            raise ValueError(
                f"compact_interval_records="
                f"{self.compact_interval_records} < 0"
            )
        if self.degrade_after_io_errors < 1:
            raise ValueError(
                f"degrade_after_io_errors="
                f"{self.degrade_after_io_errors} < 1"
            )


def _submit_payload(rec: Dict) -> Dict:
    """A journaled submit record minus its per-append stamps (``seq`` /
    ``at`` / ``crc``) — the shape compaction re-journals with fresh
    stamps into the new segment."""
    return {k: v for k, v in rec.items() if k not in ("seq", "at", "crc")}


class _DaemonRequest:
    """Daemon-side state for one accepted request: the client-visible
    record, the dedupe token, journal staging, and stream subscribers."""

    __slots__ = (
        "record", "dedupe_token", "base", "staged", "staged_index",
        "terminal_staged", "subscribers", "out", "submit_rec",
    )

    def __init__(self, record: Dict, dedupe_token: Optional[str]):
        self.record = record
        self.dedupe_token = dedupe_token
        self.base = len(record["tokens"])  # durable prefix at admission
        self.staged: List[int] = []  # tokens awaiting a journal record
        self.staged_index = self.base
        self.terminal_staged = False
        self.subscribers: List[queue.Queue] = []
        self.out = None  # the live ClusterOutput (None once terminal)
        # the journaled submit PAYLOAD (no seq/at/crc) — what compaction
        # re-emits into the fresh segment so a restart can still replay
        self.submit_rec: Optional[Dict] = None


class ServingDaemon:
    """The durable daemon shell (module docstring).

    ``frontend_factory(clock)`` builds the :class:`Frontend` — the
    daemon injects its clock so deadlines, SLO windows and journal
    timestamps share one time axis.  Construction RECOVERS: an existing
    journal at ``journal_path`` is scanned, finished requests become
    idempotent dedupe responses, unfinished ones re-admit with their
    durable token prefix forced.
    """

    def __init__(
        self,
        frontend_factory: Callable,
        journal_path: str,
        *,
        config: Optional[DaemonConfig] = None,
        clock=None,
        span_spool=None,
    ):
        self.config = config or DaemonConfig()
        self.clock = clock if clock is not None else WallClock()
        self.frontend = frontend_factory(self.clock)
        self.registry = self.frontend.registry
        self.tracer = self.frontend.tracer or NULL_TRACER
        # the per-process span log behind GET /v1/tracez; drained by
        # the tick pump, under its own lock (handler threads serving
        # tracez drain too, and a spool drain does file IO)
        self.span_spool = span_spool
        self._spool_lock = threading.Lock()
        self._lock = threading.RLock()
        self._requests: Dict[str, _DaemonRequest] = {}
        self._dedupe: Dict[str, str] = {}
        # request ids with staged journal work, in first-dirty order
        self._dirty: Dict[str, None] = {}
        self._open_count = 0  # live (non-terminal) records, O(1)
        # terminal records in completion order, for bounded retention
        self._completed: deque = deque()
        self.ticks = 0
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self._stopped = False
        # degraded mode: persistent journal failure flips this to a
        # typed reason — submissions refuse 503, /healthz exposes it,
        # the process stays up (docs/13_daemon.md degraded contract)
        self._degraded_reason: Optional[str] = None
        self._io_errors = 0  # consecutive journal append/fsync failures
        # signal flags — handlers only flip these (async-signal-safe);
        # the run loop acts on them
        self._drain_requested = False
        self._force_stop = False
        self._reload_requested = False
        r = self.registry
        self._m_records = r.counter("daemon_journal_records_total")
        self._m_fsyncs = r.counter("daemon_journal_fsyncs_total")
        self._m_dedupe_hits = r.counter("daemon_dedupe_hits_total")
        self._m_recovered = r.counter("daemon_recovered_requests_total")
        self._m_recovered_done = r.counter(
            "daemon_recovered_completions_total"
        )
        self._m_ticks = r.counter("daemon_ticks_total")
        self._m_accepted = r.counter("daemon_accepted_total")
        self._m_io_errors = r.counter(
            "daemon_journal_integrity_io_errors_total"
        )
        self._m_truncated = r.counter(
            "daemon_journal_integrity_truncated_bytes_total"
        )
        self._m_compactions = r.counter("daemon_journal_compactions_total")
        self._m_degraded_rejects = r.counter(
            "daemon_degraded_rejects_total"
        )
        self._m_kv_peer_exports = r.counter("daemon_kv_peer_exports_total")
        # observed swap/autopilot decisions flow through the frontend's
        # journal hook into REC_DECISION records
        self.frontend.set_journal(self._frontend_note)
        # drop a torn final record BEFORE reading: recovery must act on
        # exactly what stays durable, and appending after a fragment
        # would turn tolerable tail damage into mid-file corruption
        truncated = drop_torn_tail(journal_path)
        if truncated:
            self._m_truncated.inc(truncated)
        state = load_state(journal_path)
        self.journal = JournalWriter(
            journal_path, self.clock,
            fsync_batch=self.config.fsync_batch,
            next_seq=state.next_seq,
        )
        self.recoveries = state.recoveries
        self._recover(state)

    # -- journal plumbing --------------------------------------------------

    def _append(self, rec: Dict) -> Dict:
        """Journal one record, with IO-failure accounting: an
        ``OSError`` (injected or real — the record is NOT in the
        journal, see ``JournalWriter.append``'s failure contract)
        counts toward the degraded-mode threshold and re-raises for the
        call site to refuse typed."""
        before = self.journal.fsyncs
        try:
            out = self.journal.append(rec)
        except OSError as exc:
            self._m_fsyncs.inc(max(0, self.journal.fsyncs - before))
            self._note_io_error(repr(exc))
            raise
        self._io_errors = 0
        self._m_records.inc()
        self._m_fsyncs.inc(self.journal.fsyncs - before)
        return out

    def _sync(self) -> None:
        try:
            if self.journal.sync():
                self._m_fsyncs.inc()
                self._io_errors = 0
        except OSError as exc:
            # the barrier failed but every record is still in the file
            # (and the OS cache): retried next tick — persistent
            # failure crosses the degraded threshold
            self._note_io_error(repr(exc))

    def _note_io_error(self, detail: str) -> None:
        """One journal IO failure: counted, and past
        ``degrade_after_io_errors`` consecutive failures (or a wedged
        writer) the daemon enters DEGRADED mode instead of dying."""
        self._io_errors += 1
        self._m_io_errors.inc()
        if self._degraded_reason is None and (
            self.journal.wedged
            or self._io_errors >= self.config.degrade_after_io_errors
        ):
            self._enter_degraded("journal_io", detail)

    def _enter_degraded(self, reason: str, detail: str) -> None:
        """Typed degraded mode: new submissions refuse 503
        (``REJECT_DEGRADED``), in-flight work drains through the
        frontend gate, ``/healthz``/``/statez`` expose the reason, and
        the process STAYS UP — a daemon that dies mid-accept strands
        its balancer; one that drains and reports lets the fleet route
        around it.  SIGTERM still drains exit 0 from here."""
        self._degraded_reason = reason
        self.registry.counter("daemon_degraded_total", reason=reason).inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "degraded", track=DAEMON_TRACK, reason=reason,
                detail=detail,
            )
        try:
            # best-effort: the disk that caused this may refuse the note
            self._append({
                "record": REC_DECISION, "kind": "degraded",
                "reason": reason, "detail": detail,
            })
        except OSError:
            pass
        # close the admission gate and drain in-flight work; the pump
        # keeps ticking (and the journal keeps retrying its barrier)
        self.frontend.drain(max_ticks=0)

    @property
    def degraded_reason(self) -> Optional[str]:
        return self._degraded_reason

    def _frontend_note(self, kind: str, payload: Dict) -> None:
        """Frontend journal hook: operator-grade decisions (swap
        rollouts, autopilot actions, drain begin) become DECISION
        records.  Per-request submit/terminal hooks are ignored here —
        the daemon journals those itself with dedupe context.  Best
        effort: an audit-trail append on failing media must not turn a
        drain (or any frontend action) into a crash — the failure
        still counts toward the degraded threshold via ``_append``."""
        if kind in ("swap_begin", "autopilot_action", "drain_begin"):
            try:
                self._append(
                    {"record": REC_DECISION, "kind": kind, **payload}
                )
            except OSError:
                pass

    # -- recovery ----------------------------------------------------------

    def _recover(self, state) -> None:
        span = (
            self.tracer.span("recovery", track=DAEMON_TRACK)
            if self.tracer.enabled else None
        )
        replayed = completed = 0
        for entry in state.finished:
            rec = self._completed_record(entry)
            dr = _DaemonRequest(rec, entry.dedupe_token)
            dr.submit_rec = _submit_payload(entry.submit)
            self._register(dr)
            self._note_terminal(dr, was_open=False)
        for entry in state.unfinished:
            sub = entry.submit
            delivered = list(entry.tokens)
            remainder = int(sub["max_new_tokens"]) - len(delivered)
            eos = sub.get("eos_token_id")
            record = {
                "request_id": entry.request_id,
                "status": RUNNING if delivered else QUEUED,
                "finish_reason": None,
                "detail": None,
                "tokens": delivered,
                "recovered": True,
            }
            if remainder <= 0 or (eos is not None and eos in delivered):
                # the crash ate the terminal record but the durable
                # prefix already satisfies the stopping contract:
                # synthesize the terminal instead of re-admitting
                reason = (
                    "eos" if eos is not None and eos in delivered
                    else "length"
                )
                record["status"] = FINISHED
                record["finish_reason"] = reason
                dr = _DaemonRequest(record, entry.dedupe_token)
                dr.submit_rec = _submit_payload(sub)
                self._register(dr)
                self._note_terminal(dr, was_open=False)
                self._append({
                    "record": REC_TERMINAL,
                    "request_id": entry.request_id,
                    "status": FINISHED, "finish_reason": reason,
                    "n_tokens": len(delivered), "recovered": True,
                })
                completed += 1
                self._m_recovered_done.inc()
                continue
            dr = _DaemonRequest(record, entry.dedupe_token)
            dr.submit_rec = _submit_payload(sub)
            self._register(dr)
            req = Request(
                prompt=list(sub["prompt"]) + delivered,
                max_new_tokens=remainder,
                sampling=SamplingParams(**sub.get("sampling") or {}),
                eos_token_id=eos,
                request_id=entry.request_id,
                client_id=sub.get("client_id"),
                priority=int(sub.get("priority") or 0),
                deadline=sub.get("deadline"),
                on_token=self._make_on_token(dr),
            )
            out = self.frontend.submit(req)
            if out.status == REJECTED:
                # loud, typed loss: the journal promised this request a
                # future the restarted config no longer affords
                self._terminal_now(
                    dr, REJECTED, out.finish_reason, detail=out.detail
                )
                continue
            dr.out = out
            self._open_count += 1
            replayed += 1
            self._m_recovered.inc()
        if state.entries or state.torn_records:
            self._append({
                "record": REC_RECOVERY,
                "replayed": replayed,
                "already_complete": completed,
                "finished_in_journal": len(state.finished),
                "torn_records": state.torn_records,
            })
        self._enforce_retention()  # recovery records are all journaled
        if span is not None:
            span.finish(replayed=replayed, completed=completed)

    @staticmethod
    def _completed_record(entry) -> Dict:
        term = entry.terminal
        return {
            "request_id": entry.request_id,
            "status": term.get("status", FINISHED),
            "finish_reason": term.get("finish_reason"),
            "detail": term.get("detail"),
            "tokens": list(entry.tokens),
            "recovered": True,
        }

    def _register(self, dr: _DaemonRequest) -> None:
        self._requests[dr.record["request_id"]] = dr
        if dr.dedupe_token:
            self._dedupe[dr.dedupe_token] = dr.record["request_id"]

    def _note_terminal(self, dr: _DaemonRequest, was_open: bool) -> None:
        """Terminal bookkeeping: keep the open count O(1) and queue the
        record for retention.  Eviction itself is deferred to
        :meth:`_enforce_retention` AFTER the tick's journal flush — a
        record evicted while its terminal/tokens were still staged
        would vanish from the journal too, and a restart would replay
        (and duplicate) an already-completed request."""
        if was_open:
            self._open_count = max(0, self._open_count - 1)
        self._completed.append(dr.record["request_id"])

    def _enforce_retention(self) -> None:
        """Evict the oldest completed records past the retention bound
        (their in-RAM dedupe horizon ends; the journal keeps
        everything).  Only ever called with the journal flushed; a head
        record that somehow still has staged work stops the sweep."""
        while len(self._completed) > self.config.completed_retention:
            old = self._completed[0]
            if old in self._dirty:
                return  # staged journal work: flush must win first
            self._completed.popleft()
            gone = self._requests.get(old)
            if gone is None or gone.out is not None:
                continue  # superseded id or somehow live again: skip
            del self._requests[old]
            if gone.dedupe_token and self._dedupe.get(
                gone.dedupe_token
            ) == old:
                del self._dedupe[gone.dedupe_token]

    # -- admission ---------------------------------------------------------

    def submit(
        self, request: Request, dedupe_token: Optional[str] = None,
        phase: Optional[str] = None,
        trace: Optional[TraceContext] = None,
    ) -> Dict:
        """Accept one request: dedupe first (an already-seen token
        returns the live/completed record instead of re-admitting —
        client retries across a daemon crash are idempotent), then the
        role gate, then the frontend's typed admission gate, then the
        DURABLE accept — the submit record is fsynced before this
        returns.  ``phase="decode"`` marks a router-issued handoff
        continuation, the only submissions a ``decode``-role daemon
        takes.  ``trace`` is the wire-adopted trace context (the
        router's fork for this crossing): bound on the tracer so every
        span this daemon records for the request carries the fleet
        trace id, unbound when the request turns terminal or is
        refused."""
        from tpu_parallel.fleet.roles import (
            PHASE_DECODE,
            REJECT_ROLE,
            ROLE_DECODE,
        )

        with self._lock:
            dedupe_token = dedupe_token or request.dedupe_token
            if dedupe_token and dedupe_token in self._dedupe:
                self._m_dedupe_hits.inc()
                # a SNAPSHOT, like result(): the live record mutates
                # under the tick while the HTTP thread serializes this
                return self.result(self._dedupe[dedupe_token])
            record = {
                "request_id": request.request_id,
                "status": QUEUED,
                "finish_reason": None,
                "detail": None,
                "tokens": [],
                "recovered": False,
            }
            if (
                self.config.role == ROLE_DECODE
                and phase != PHASE_DECODE
            ):
                # a healthy daemon refusing on ROLE is routing policy,
                # not sickness: typed 503 so the router excludes it for
                # this request without feeding the breaker
                self.registry.counter("daemon_role_rejects_total").inc()
                record["status"] = REJECTED
                record["finish_reason"] = REJECT_ROLE
                record["detail"] = (
                    "decode-role daemon takes only handoff continuations"
                )
                return record
            if self._degraded_reason is not None:
                # the durability substrate is gone: refusing typed (the
                # HTTP layer maps this to 503) beats acknowledging work
                # a dead journal cannot promise to keep
                self._m_degraded_rejects.inc()
                record["status"] = REJECTED
                record["finish_reason"] = REJECT_DEGRADED
                record["detail"] = (
                    f"daemon degraded: {self._degraded_reason}"
                )
                return record
            if trace is not None and self.tracer.enabled:
                # bind BEFORE frontend.submit so the queue span the
                # admission records already carries the fleet trace id
                self.tracer.bind_trace(request.request_id, trace)
            dr = _DaemonRequest(record, dedupe_token)
            request.on_token = self._make_on_token(dr)
            now = self.clock()
            out = self.frontend.submit(request)
            if out.status == REJECTED:
                self.tracer.release_trace(request.request_id)
                record["status"] = REJECTED
                record["finish_reason"] = out.finish_reason
                record["detail"] = out.detail
                return record  # rejections are not journaled/deduped
            dr.out = out
            sampling = request.sampling
            payload = {
                "record": REC_SUBMIT,
                "request_id": request.request_id,
                "dedupe_token": dedupe_token,
                "client_id": request.client_id,
                # trace-schema workload fields (serve_bench
                # --workload replays journals like traces)
                "arrival": round(now, 6),
                "prompt": [int(t) for t in request.prompt],
                "prompt_len": len(request.prompt),
                "prefix_group": 0,
                "priority": request.priority,
                "deadline": request.deadline,
                "max_new_tokens": request.max_new_tokens,
                "eos_token_id": request.eos_token_id,
                "sampling": {
                    "temperature": sampling.temperature,
                    "top_k": sampling.top_k,
                    "top_p": sampling.top_p,
                },
            }
            try:
                self._append(payload)
            except OSError as exc:
                # an accept we cannot make durable must not exist: the
                # frontend admission is withdrawn (so no un-journaled
                # request keeps generating and no dedupe entry vouches
                # for it) and the refusal is TYPED — the append failure
                # already counted toward the degraded threshold
                self.frontend.cancel(
                    request.request_id, reason=REJECT_JOURNAL
                )
                self.tracer.release_trace(request.request_id)
                record["status"] = REJECTED
                record["finish_reason"] = REJECT_JOURNAL
                record["detail"] = repr(exc)
                return record
            except Exception:
                self.frontend.cancel(
                    request.request_id, reason=REJECT_JOURNAL
                )
                self.tracer.release_trace(request.request_id)
                raise
            # registered only AFTER the durable append: a failed write
            # leaves no acknowledged-but-undurable state behind
            dr.submit_rec = payload
            self._register(dr)
            self._open_count += 1
            self._m_accepted.inc()
            return self.result(request.request_id)

    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        with self._lock:
            return self.frontend.cancel(request_id, reason=reason)

    def result(self, request_id: str) -> Optional[Dict]:
        with self._lock:
            dr = self._requests.get(request_id)
            if dr is None:
                return None
            rec = dict(dr.record)
            rec["tokens"] = list(rec["tokens"])
            return rec

    def subscribe(self, request_id: str):
        """Stream attachment: returns ``(snapshot, q)`` — the tokens
        already delivered plus a queue of future :class:`StreamEvent`s
        (``q`` is None when the request is already terminal; the
        snapshot record tells the subscriber how it ended)."""
        with self._lock:
            dr = self._requests.get(request_id)
            if dr is None:
                return None, None
            snapshot = self.result(request_id)
            if dr.out is None:  # terminal
                return snapshot, None
            q: queue.Queue = queue.Queue()
            dr.subscribers.append(q)
            return snapshot, q

    def unsubscribe(self, request_id: str, q) -> None:
        """Detach a stream queue (the HTTP layer calls this when the
        SSE connection ends, finished or disconnected)."""
        with self._lock:
            dr = self._requests.get(request_id)
            if dr is not None and q in dr.subscribers:
                dr.subscribers.remove(q)

    # -- delivery (runs inside frontend.step under the daemon lock) --------

    def _make_on_token(self, dr: _DaemonRequest):
        def on_token(ev: StreamEvent) -> None:
            record = dr.record
            if ev.token >= 0:
                record["status"] = RUNNING
                record["tokens"].append(int(ev.token))
                dr.staged.append(int(ev.token))
            if ev.finished:
                out = dr.out
                record["status"] = (
                    out.status if out is not None else FINISHED
                )
                record["finish_reason"] = ev.finish_reason
                if out is not None:
                    record["detail"] = out.detail
                dr.terminal_staged = True
                was_open = dr.out is not None
                dr.out = None
                if record["request_id"] in self._requests:
                    self._note_terminal(dr, was_open)
                self.tracer.release_trace(record["request_id"])
            if dr.staged or dr.terminal_staged:
                self._dirty[record["request_id"]] = None
            for q in dr.subscribers:
                q.put(StreamEvent(
                    request_id=record["request_id"],
                    token=ev.token,
                    index=dr.base + ev.index if ev.index >= 0 else -1,
                    finished=ev.finished,
                    finish_reason=ev.finish_reason,
                ))
        return on_token

    def _flush_dirty(self) -> None:
        """Journal this tick's deliveries: one TOKENS record per request
        with new tokens, then its TERMINAL record when it ended — order
        within a request is what replay correctness rides on.  An IO
        failure mid-flush keeps the unflushed remainder staged (the
        failed append left nothing in the journal, so the next tick
        retries exactly the missing records — token records fold by
        index, so even an overlap would be idempotent)."""
        rids = list(self._dirty)
        self._dirty = {}
        for i, rid in enumerate(rids):
            dr = self._requests.get(rid)
            if dr is None:
                continue
            try:
                if dr.staged:
                    self._append({
                        "record": REC_TOKENS,
                        "request_id": rid,
                        "index": dr.staged_index,
                        "tokens": dr.staged,
                    })
                    dr.staged_index += len(dr.staged)
                    dr.staged = []
                if dr.terminal_staged:
                    rec = dr.record
                    self._append({
                        "record": REC_TERMINAL,
                        "request_id": rid,
                        "status": rec["status"],
                        "finish_reason": rec["finish_reason"],
                        "n_tokens": len(rec["tokens"]),
                    })
                    dr.terminal_staged = False
            except OSError:
                # this record and everything after it stays dirty; the
                # error already counted toward the degraded threshold
                for rest in rids[i:]:
                    self._dirty[rest] = None
                return

    def _terminal_now(
        self, dr: _DaemonRequest, status: str, reason: Optional[str],
        detail: Optional[str] = None,
    ) -> None:
        """Immediate journaled terminal outside the tick path (recovery
        rejections)."""
        rec = dr.record
        rec["status"] = status
        rec["finish_reason"] = reason
        rec["detail"] = detail
        was_open = dr.out is not None
        dr.out = None
        self._note_terminal(dr, was_open)
        self._append({
            "record": REC_TERMINAL,
            "request_id": rec["request_id"],
            "status": status, "finish_reason": reason,
            "n_tokens": len(rec["tokens"]),
        })

    def _compact(self) -> None:
        """Journal segment rotation: snapshot the live state (every
        retained request's submit payload, durable token prefix, and
        terminal when it has one — all record kinds replay already
        understands) into a fresh segment and retire the old one.
        Restart replay after a long uptime reads O(open + retained)
        records instead of O(lifetime).  Only called with the tick's
        journal flushed (nothing staged), so the snapshot is exactly
        the durable state."""
        snapshot: List[Dict] = []
        for rid, dr in self._requests.items():
            if dr.submit_rec is None:
                continue  # defensive: nothing replayable without it
            snapshot.append(dict(dr.submit_rec))
            toks = [int(t) for t in dr.record["tokens"]]
            if toks:
                snapshot.append({
                    "record": REC_TOKENS, "request_id": rid,
                    "index": 0, "tokens": toks,
                })
            if dr.out is None:  # terminal (finished/rejected/cancelled)
                snapshot.append({
                    "record": REC_TERMINAL, "request_id": rid,
                    "status": dr.record["status"],
                    "finish_reason": dr.record["finish_reason"],
                    "n_tokens": len(toks),
                })
        try:
            written = self.journal.rotate(snapshot)
        except OSError as exc:
            self._note_io_error(repr(exc))
            return
        self._io_errors = 0
        self._m_compactions.inc()
        self._m_records.inc(written)
        if self.tracer.enabled:
            self.tracer.instant(
                "compact", track=DAEMON_TRACK,
                snapshot_records=written, open=self._open_count,
            )

    # -- the pump ----------------------------------------------------------

    def tick(self) -> List[StreamEvent]:
        """One daemon tick: a frontend step, then the tick's journal
        batch (tokens + terminals) and ONE batched fsync window."""
        with self._lock:
            events = self.frontend.step()
            self._flush_dirty()
            self._sync()
            self._enforce_retention()
            ci = self.config.compact_interval_records
            if (
                ci
                and not self._dirty
                and self._degraded_reason is None
                and self.journal.records_since_rotate >= ci
            ):
                self._compact()
            self.ticks += 1
            self._m_ticks.inc()
            self.registry.gauge("daemon_open_requests").set(
                self._open_count
            )
            self.registry.gauge("daemon_draining").set(
                1.0 if self._draining else 0.0
            )
            self.registry.gauge("daemon_degraded").set(
                0.0 if self._degraded_reason is None else 1.0
            )
        # span IO happens OUTSIDE the daemon lock: a slow (or
        # fault-injected) spool write must not stall admission
        self._drain_spool()
        return events

    def _drain_spool(self) -> None:
        """Flush newly-recorded spans to the per-process span log.  A
        spool write failure is logged as a skip by the spool itself;
        tracing is never allowed to take the daemon down."""
        if self.span_spool is None:
            return
        with self._spool_lock:
            try:
                self.span_spool.drain(self.tracer)
            except OSError:
                pass

    def trace_payload(self, trace_id: Optional[str] = None) -> Dict:
        """The ``GET /v1/tracez`` body: this process's spooled span
        records (optionally filtered to one trace), plus the damage
        counters ``read_span_log`` kept while skipping bad lines."""
        if self.span_spool is None:
            return {
                "proc": f"daemon:{self.config.role or 'serve'}",
                "pid": os.getpid(),
                "records": [],
                "skipped": {},
            }
        with self._spool_lock:
            try:
                self.span_spool.drain(self.tracer)
            except OSError:
                pass
            records, skipped = read_span_log(
                self.span_spool.path, trace_id=trace_id
            )
        return {
            "proc": f"daemon:{self.config.role or 'serve'}",
            "pid": os.getpid(),
            "records": records,
            "skipped": skipped,
        }

    def install_signals(self) -> None:
        """Wire the POSIX contract (main thread only): SIGTERM/SIGINT =
        graceful drain, repeated = force fast shutdown, SIGHUP = weight
        reload through the swap path.  Handlers only set flags."""
        _signal.signal(_signal.SIGTERM, self._on_term)
        _signal.signal(_signal.SIGINT, self._on_term)
        if hasattr(_signal, "SIGHUP"):
            _signal.signal(_signal.SIGHUP, self._on_hup)

    def _on_term(self, signum, frame) -> None:
        if self._drain_requested:
            self._force_stop = True
        else:
            self._drain_requested = True

    def _on_hup(self, signum, frame) -> None:
        self._reload_requested = True

    def request_drain(self) -> None:
        """Programmatic SIGTERM equivalent (tests, embedders)."""
        self._on_term(None, None)

    def request_reload(self) -> None:
        self._reload_requested = True

    def _begin_drain(self) -> None:
        self._draining = True
        self._drain_deadline = self.clock() + self.config.grace_seconds
        self.registry.counter(
            "daemon_signals_total", signal="term"
        ).inc()
        with self._lock:
            if self.tracer.enabled:
                self.tracer.instant(
                    "drain_begin", track=DAEMON_TRACK,
                    open=self._open_count,
                )
            # close the gate, gate every engine, pull queued work back —
            # then keep pumping ticks under the grace window
            self.frontend.drain(max_ticks=0)

    def _do_reload(self) -> None:
        self._reload_requested = False
        self.registry.counter("daemon_signals_total", signal="hup").inc()
        path = self.config.reload_path

        def decide(verdict, **extra):
            # under the lock: HTTP submit threads append concurrently.
            # Best effort — a reload verdict on failing media must not
            # kill the pump (the failure still counts via _append).
            with self._lock:
                try:
                    self._append({
                        "record": REC_DECISION, "kind": "reload",
                        "verdict": verdict, **extra,
                    })
                except OSError:
                    pass

        if path is None:
            return decide("no_reload_path")
        import json as _json
        try:
            with iofaults.open_file(path, encoding="utf-8") as fh:
                spec = _json.load(fh)
        except (OSError, ValueError) as exc:
            return decide("unreadable", detail=repr(exc))
        if not spec.get("checkpoint_dir"):
            return decide("no_checkpoint_dir")
        with self._lock:
            status = self.frontend.begin_swap(
                checkpoint_dir=spec["checkpoint_dir"],
                step=spec.get("step"),
                version=spec.get("version"),
            )
            try:
                self._append({
                    "record": REC_DECISION, "kind": "reload",
                    "verdict": (
                        status.get("verdict") or status.get("state")
                    ),
                })
            except OSError:
                pass

    def _shutdown(self, clean: bool) -> int:
        with self._lock:
            self._stopped = True
            open_req = self._open_count
            # a degraded (dead-disk) exit must still honor the signal
            # contract: the exit CODE is the promise, the shutdown
            # record is best-effort on media that may refuse it
            self._flush_dirty()
            try:
                self._append({
                    "record": REC_SHUTDOWN, "clean": clean,
                    "open_requests": open_req,
                })
                self.journal.close()
            except OSError:
                pass
        if self.tracer.enabled:
            self.tracer.instant(
                "shutdown", track=DAEMON_TRACK, clean=clean,
                open=open_req,
            )
        return EXIT_CLEAN if clean else EXIT_FORCED

    def run(self, max_ticks: Optional[int] = None) -> int:
        """The pump: tick until shut down.  Returns the process exit
        code — 0 for a clean drained exit, 1 for a forced fast shutdown
        (open work waits in the journal for the next recovery)."""
        ticks = 0
        while max_ticks is None or ticks < max_ticks:
            if self._force_stop:
                self.registry.counter(
                    "daemon_signals_total", signal="term_force"
                ).inc()
                return self._shutdown(clean=not self.frontend.has_work())
            if self._reload_requested:
                self._do_reload()
            if self._drain_requested and not self._draining:
                self._begin_drain()
            self.tick()
            ticks += 1
            if self._draining:
                if not self.frontend.has_work():
                    return self._shutdown(clean=True)
                if self.clock() > self._drain_deadline:
                    # grace blown: abandon the remainder to the journal
                    return self._shutdown(clean=False)
            elif not self.frontend.has_work():
                self.clock.sleep(self.config.idle_sleep_seconds)
        return EXIT_FORCED  # max_ticks exhausted with the daemon still up

    # -- peer KV exchange (fleet) ------------------------------------------

    def export_hot_kv(self, max_blocks: int = 16) -> List:
        """Snapshot the hottest radix-cached prefixes from the first
        live replica that pages any, for shipment to a fleet peer
        (warm-start on join/restart, drain-forward on leave — see
        ``fleet/router.py`` and docs/14_fleet.md).  Returns a list of
        :class:`~tpu_parallel.serving.kv_hierarchy.KVPrefixExport`;
        empty when no replica runs a radix cache or nothing is hot."""
        from tpu_parallel.cluster.replica import DEAD as _REPLICA_DEAD

        with self._lock:
            if self._stopped:
                return []
            for handle in self.frontend.replicas:
                if handle.health == _REPLICA_DEAD:
                    continue
                exporter = getattr(
                    handle.engine, "export_hot_prefixes", None
                )
                if exporter is None:
                    continue
                exports = exporter(max_blocks=max_blocks)
                if exports:
                    self._m_kv_peer_exports.inc(len(exports))
                    return list(exports)
            return []

    def export_request_kv(self, request_id: str) -> List:
        """Export ONE live request's written KV prefix — the donor half
        of the prefill→decode disaggregation handoff: the router calls
        this on the prefill daemon at first-token time and ships the
        blocks to the chosen decode peer, so the forced-prefix
        continuation admits against a warm radix tree instead of
        re-prefilling.  Empty when the request is unknown, not paged,
        or has less than one full block written — the router's typed
        fallback (colocated decode) covers every empty answer."""
        with self._lock:
            if self._stopped:
                return []
            dr = self._requests.get(request_id)
            if dr is None or dr.out is None:
                return []
            export = self.frontend.export_request_kv(request_id)
            if export is None:
                return []
            self._m_kv_peer_exports.inc()
            return [export]

    def kv_occupancy(self) -> Dict[str, float]:
        """Device/host KV-tier block occupancy summed over live
        replicas — carried on ``/healthz`` so the fleet router's
        placement and the autopilot's role lever see pressure, not just
        liveness."""
        from tpu_parallel.cluster.replica import DEAD as _REPLICA_DEAD

        with self._lock:
            device_used = device_total = host_used = 0
            disk_used = disk_total = seeded_chains = 0
            disk_restores = disk_restore_failures = 0
            manifest_age = None
            for handle in self.frontend.replicas:
                if handle.health == _REPLICA_DEAD:
                    continue
                pool = getattr(handle.engine, "pool", None)
                alloc = getattr(pool, "allocator", None)
                if alloc is not None:
                    device_total += int(alloc.n_blocks)
                    device_used += int(alloc.n_blocks) - int(alloc.n_free)
                radix = getattr(handle.engine, "_radix", None)
                if radix is not None:
                    host_used += int(
                        getattr(radix, "host_blocks_in_use", 0)
                    )
                    store = getattr(radix, "disk", None)
                    if store is not None:
                        disk_used += int(store.blocks_in_use)
                        disk_total += int(store.capacity_blocks)
                        seeded_chains += int(
                            getattr(radix, "disk_seeded_chains", 0)
                        )
                        disk_restores += int(
                            getattr(radix, "disk_restores", 0)
                        )
                        disk_restore_failures += int(
                            getattr(radix, "disk_restore_failures", 0)
                        )
                        age = float(store.manifest_age_seconds())
                        if manifest_age is None or age > manifest_age:
                            manifest_age = age
            occ = {
                "device_blocks_used": device_used,
                "device_blocks_total": device_total,
                "host_blocks_used": host_used,
            }
            # disk-tier rows only when an SSD tier is attached — old
            # routers .get() these, new ones see the fraction + the
            # manifest's staleness in one probe
            if disk_total:
                occ["disk_blocks_used"] = disk_used
                occ["disk_blocks_total"] = disk_total
                occ["disk_seeded_chains"] = seeded_chains
                occ["disk_restores"] = disk_restores
                occ["disk_restore_failures"] = disk_restore_failures
                occ["manifest_age_seconds"] = round(
                    manifest_age or 0.0, 3
                )
            return occ

    def import_peer_kv(self, exports) -> Dict[str, int]:
        """Land already-decoded peer exports into every live replica's
        prefix cache, inheriting the migration layer's verify-or-refuse
        contract — corrupt or incompatible blocks land as typed refusal
        verdicts, never as served bytes.  Returns verdict counts
        (``imported`` / ``integrity`` / ``weights_version`` / ...)."""
        from tpu_parallel.cluster.migration import land_exports
        from tpu_parallel.cluster.replica import DEAD as _REPLICA_DEAD

        with self._lock:
            counts: Dict[str, int] = {}
            for handle in self.frontend.replicas:
                if handle.health == _REPLICA_DEAD:
                    continue
                for verdict, n in land_exports(
                    handle.engine, exports
                ).items():
                    counts[verdict] = counts.get(verdict, 0) + n
            for verdict, n in counts.items():
                self.registry.counter(
                    "daemon_kv_peer_imports_total", status=verdict
                ).inc(n)
            return counts

    # -- introspection -----------------------------------------------------

    @property
    def role(self) -> str:
        """This daemon's fleet role (``prefill``/``decode``/``mixed``) —
        fixed at config time, advertised on ``/healthz``."""
        return self.config.role

    def status(self) -> Dict:
        with self._lock:
            open_req = self._open_count
            return {
                "role": self.config.role,
                "draining": self._draining,
                "stopped": self._stopped,
                "degraded_reason": self._degraded_reason,
                "ticks": self.ticks,
                "open_requests": open_req,
                "requests": len(self._requests),
                "recoveries": self.recoveries,
                "journal": {
                    "path": self.journal.path,
                    "records": self.journal.records,
                    "fsyncs": self.journal.fsyncs,
                    "next_seq": self.journal.next_seq,
                    "rotations": self.journal.rotations,
                    "io_errors": int(self._m_io_errors.value),
                    "wedged": self.journal.wedged,
                },
            }
