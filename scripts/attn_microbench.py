"""Microbenchmark attention implementations at bench shapes on the real chip.

Times fwd+bwd of the XLA reference path vs the Pallas flash kernel across
block sizes, standalone (outside the full model), to locate the attention
share of the MFU gap.  Prints one JSON line per variant.

Usage: python scripts/attn_microbench.py [batch] [seq] [heads] [head_dim]
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def main():
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    s = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    h = int(sys.argv[3]) if len(sys.argv) > 3 else 12
    d = int(sys.argv[4]) if len(sys.argv) > 4 else 64

    from tpu_parallel.models.layers import causal_attention
    from tpu_parallel.ops.flash_attention import flash_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, s, h, d), jnp.bfloat16)

    # causal FLOPs: 2 matmuls (QK^T, AV) x 2*s*s*d x 0.5 (triangle), x3.5 bwd
    flops = 3.5 * b * h * (2 * 2 * s * s * d * 0.5)

    def bench(name, fn, **kw):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32))

        step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        try:
            out = step(q, k, v)
            jax.block_until_ready(out)
            n = 20
            t0 = time.perf_counter()
            for _ in range(n):
                out = step(q, k, v)
            jax.block_until_ready(out)
            # device->host read: block_until_ready can lie on some transports
            float(jnp.sum(out[0].astype(jnp.float32)))
            dt = (time.perf_counter() - t0) / n
            print(
                json.dumps(
                    {
                        "impl": name,
                        **kw,
                        "ms": round(dt * 1e3, 3),
                        "tflops": round(flops / dt / 1e12, 1),
                    }
                ),
                flush=True,
            )
        except Exception as e:  # compile failures shouldn't kill the sweep
            print(json.dumps({"impl": name, **kw, "error": repr(e)[:120]}), flush=True)

    bench("xla", causal_attention)
    for bq, bk in [(128, 128), (256, 128), (256, 256), (512, 256), (512, 512), (1024, 512), (512, 1024), (1024, 1024)]:
        if bq > s or bk > s:
            continue
        bench(
            "flash",
            functools.partial(flash_attention, block_q=bq, block_k=bk),
            bq=bq,
            bk=bk,
        )


if __name__ == "__main__":
    main()
