"""Continuous-batching engine tests: greedy parity with the static path,
slot reuse across staggered arrivals, scheduler policies, per-request
sampling isolation, and the prefill fast path (bucketing / batching /
chunking / prefix reuse — all pinned token-identical to the exact
batch-1-prefill engine)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from _spec_drafters import AntiOracleDrafter, OracleDrafter
from _spec_drafters import ref_map as _ref_map

from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate, padded_prefill_inputs
from tpu_parallel.serving import (
    EXPIRED,
    FINISHED,
    REJECTED,
    FIFOScheduler,
    PrefixCache,
    Request,
    RequestOutput,
    SamplingParams,
    SchedulerConfig,
    ServingEngine,
    ServingMetrics,
    default_prefill_buckets,
    percentile,
)


def _build(rng, n_rows=3, prompt_len=5, **overrides):
    cfg = tiny_test(dtype=jnp.float32, remat=False, **overrides)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (n_rows, prompt_len), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, prompt, train=False
    )["params"]
    return cfg, model, prompt, params


def _req(prompt_row, n_new, **kwargs):
    return Request(
        prompt=[int(t) for t in np.asarray(prompt_row)],
        max_new_tokens=n_new,
        **kwargs,
    )


@pytest.mark.parametrize("variant", ["gpt", "rope"])
def test_engine_greedy_parity_simultaneous(rng, variant):
    """Acceptance: N simultaneously-arriving greedy requests through the
    engine are token-identical to static generate() on the same prompts —
    learned-pos (GPT-2) and RoPE variants."""
    overrides = dict(
        gpt={}, llama={}, rope=dict(positional="rope", norm="rmsnorm")
    )[variant]
    cfg, model, prompt, params = _build(rng, n_rows=3, **overrides)
    want = np.asarray(generate(model, params, prompt, max_new_tokens=8))
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=3),
    )
    outs = [eng.add_request(_req(prompt[i], 8)) for i in range(3)]
    eng.run()
    for i, out in enumerate(outs):
        assert out.status == FINISHED and out.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(out.tokens), want[i], err_msg=f"request {i}"
        )


def test_engine_staggered_arrivals_match_reference(rng):
    """Acceptance: requests joining mid-flight into freed slots (pool of 2,
    4 requests of different prompt lengths and budgets, arrivals spread
    over ticks) each match a one-request-at-a-time reference decode."""
    cfg, model, _, params = _build(rng)
    lens, budgets = [3, 5, 4, 6], [6, 4, 8, 5]
    rows = [
        jax.random.randint(
            jax.random.fold_in(rng, i), (1, L), 1, cfg.vocab_size
        )
        for i, L in enumerate(lens)
    ]
    refs = [
        np.asarray(generate(model, params, r, max_new_tokens=n))
        for r, n in zip(rows, budgets)
    ]
    eng = ServingEngine(model, params, n_slots=2)
    outs = [eng.add_request(_req(rows[0][0], budgets[0]))]
    outs.append(eng.add_request(_req(rows[1][0], budgets[1])))
    eng.step(), eng.step()
    outs.append(eng.add_request(_req(rows[2][0], budgets[2])))
    eng.step(), eng.step()
    outs.append(eng.add_request(_req(rows[3][0], budgets[3])))
    eng.run()
    for i, (out, ref) in enumerate(zip(outs, refs)):
        assert out.status == FINISHED, f"request {i}: {out.status}"
        np.testing.assert_array_equal(
            np.asarray(out.tokens), ref[0], err_msg=f"request {i}"
        )
    # four requests through two slots => slots were reused
    assert eng.metrics.finished == 4 and eng.pool.n_free == 2


def test_slot_reuse_after_completion(rng):
    """A single-slot pool serves requests strictly in sequence: the second
    runs only after the first retires and reuses its slot, with outputs
    unpolluted by the slot's previous occupant.  Per-step tick: the
    admitted-but-not-finished checkpoint below needs one-token ticks."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    refs = [
        np.asarray(generate(model, params, prompt[i : i + 1], max_new_tokens=5))
        for i in range(2)
    ]
    eng = ServingEngine(model, params, n_slots=1, decode_steps_per_tick=1)
    a = eng.add_request(_req(prompt[0], 5))
    b = eng.add_request(_req(prompt[1], 5))
    # first tick admits only request a (one slot)
    eng.step()
    assert a.status == "running" and b.status == "queued"
    eng.run()
    np.testing.assert_array_equal(np.asarray(a.tokens), refs[0][0])
    np.testing.assert_array_equal(np.asarray(b.tokens), refs[1][0])
    assert eng.pool.n_free == 1


def test_eos_retires_before_max_new_tokens(rng):
    """EOS stop: the engine retires the slot at the first EOS (included in
    the output) instead of decoding to the length budget."""
    cfg, model, prompt, params = _build(rng, n_rows=1)
    ref = list(
        np.asarray(generate(model, params, prompt, max_new_tokens=8))[0]
    )
    eos = int(ref[2])
    stop = ref.index(eos)  # first occurrence (<= 2, well before 8)
    eng = ServingEngine(model, params, n_slots=2)
    out = eng.add_request(_req(prompt[0], 8, eos_token_id=eos))
    eng.run()
    assert out.finish_reason == "eos"
    assert out.tokens == ref[: stop + 1]
    assert eng.pool.n_free == 2  # slot returned


def test_admission_control_rejects_when_full(rng):
    """max_queue admission control: submissions beyond the queue bound are
    REJECTED at submit time while the pool is busy."""
    cfg, model, prompt, params = _build(rng, n_rows=3)
    eng = ServingEngine(
        model, params, n_slots=1,
        scheduler=SchedulerConfig(max_queue=1),
    )
    a = eng.add_request(_req(prompt[0], 6))
    eng.step()  # a occupies the only slot; queue is empty again
    b = eng.add_request(_req(prompt[1], 6))
    c = eng.add_request(_req(prompt[2], 6))
    assert b.status == "queued"
    assert c.status == REJECTED and c.finish_reason == "queue_full"
    eng.run()
    assert a.status == FINISHED and b.status == FINISHED
    assert c.tokens == []


def test_queue_timeout_expires_requests(rng):
    """max_wait: a queued request whose wait exceeds the budget EXPIRES
    instead of serving a long-abandoned client (deterministic via an
    injected clock)."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    t = [0.0]
    eng = ServingEngine(
        model, params, n_slots=1,
        scheduler=SchedulerConfig(max_wait=10.0),
        clock=lambda: t[0],
    )
    seen = []
    a = eng.add_request(_req(prompt[0], 6))
    b = eng.add_request(
        _req(prompt[1], 6, on_token=lambda ev: seen.append(ev))
    )
    eng.step()  # a takes the slot, b queued at t=0
    t[0] = 11.0
    events = eng.run()
    assert a.status == FINISHED
    assert b.status == EXPIRED and b.tokens == []
    assert b.finish_reason == "max_wait"
    # expiry is asynchronous: the stream gets a tokenless terminal event
    assert len(seen) == 1 and seen[0].finished and seen[0].token == -1
    assert seen[0].finish_reason == "max_wait"
    assert any(
        ev.request_id == b.request.request_id and ev.finished
        for ev in events
    )
    assert eng.metrics.expired == 1
    assert eng.metrics.tokens_out == 6  # a's tokens only, not the notification


def test_per_request_sampling_isolation(rng):
    """Per-slot sampling knobs: a greedy request, a temp-with-top_k=1
    request (deterministically argmax — proves the per-row filter applies
    to ITS row), and a hot-temperature request share ticks; the two
    deterministic rows must match the static greedy reference exactly."""
    cfg, model, prompt, params = _build(rng, n_rows=1)
    ref = np.asarray(generate(model, params, prompt, max_new_tokens=6))[0]
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=4),
        rng=jax.random.PRNGKey(3),
    )
    greedy = eng.add_request(_req(prompt[0], 6))
    topk1 = eng.add_request(
        _req(prompt[0], 6, sampling=SamplingParams(temperature=1.0, top_k=1))
    )
    hot = eng.add_request(
        _req(prompt[0], 6, sampling=SamplingParams(temperature=4.0))
    )
    eng.run()
    np.testing.assert_array_equal(np.asarray(greedy.tokens), ref)
    np.testing.assert_array_equal(np.asarray(topk1.tokens), ref)
    assert len(hot.tokens) == 6
    assert all(0 <= tok < cfg.vocab_size for tok in hot.tokens)


def test_engine_int8_cache_matches_static_int8(rng):
    """The engine's slot pool composes with kv_cache_dtype="int8": both
    paths quantize identically, so engine greedy tokens equal static
    generate() on the same int8-cache model."""
    cfg, model, prompt, params = _build(rng, n_rows=2, kv_cache_dtype="int8")
    want = np.asarray(generate(model, params, prompt, max_new_tokens=6))
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
    )
    outs = [eng.add_request(_req(prompt[i], 6)) for i in range(2)]
    eng.run()
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(np.asarray(out.tokens), want[i])


def test_streaming_events_and_metrics(rng):
    """Incremental delivery + observability: on_token fires once per token
    in order, and the summary's counters/latency stats are coherent."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    seen = []
    # per-step tick: the occupancy-mean assertion needs ticks where the
    # request is still in its slot at tick end (a fused tick would
    # finish it within the first decode tick)
    eng = ServingEngine(model, params, n_slots=2, decode_steps_per_tick=1)
    out = eng.add_request(
        _req(prompt[0], 5, on_token=lambda ev: seen.append(ev))
    )
    eng.run()
    assert [ev.token for ev in seen] == out.tokens
    assert [ev.index for ev in seen] == list(range(5))
    assert seen[-1].finished and seen[-1].finish_reason == "length"
    s = eng.metrics.summary()
    assert s["finished"] == 1 and s["tokens_out"] == 5
    assert s["ttft_ms_p50"] is not None and s["ttft_ms_p50"] >= 0
    assert 0.0 < s["slot_occupancy_mean"] <= 1.0
    assert s["tokens_per_sec"] is None or s["tokens_per_sec"] > 0


def test_capacity_rejected_at_submit(rng):
    cfg, model, prompt, params = _build(rng, n_rows=1)
    eng = ServingEngine(model, params, n_slots=1)
    out = eng.add_request(_req(prompt[0], cfg.seq_len))
    assert out.status == REJECTED and out.finish_reason == "capacity"
    assert "seq_len" in out.detail


def test_scheduler_policies_host_only():
    """Pure host-side scheduler behavior: FIFO order, prefill budget,
    expiry — no device work."""
    sched = FIFOScheduler(SchedulerConfig(max_prefills_per_tick=2))
    outs = [
        RequestOutput(Request(prompt=[1]), arrival_time=float(i))
        for i in range(5)
    ]
    for out in outs:
        assert sched.submit(out)
    assert sched.depth == 5
    first = sched.schedule(n_free=4, now=10.0)
    assert first == outs[:2]  # prefill budget caps below free slots
    second = sched.schedule(n_free=1, now=10.0)
    assert second == outs[2:3]  # free slots cap below the budget
    timed = FIFOScheduler(SchedulerConfig(max_wait=5.0))
    old = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    new = RequestOutput(Request(prompt=[1]), arrival_time=8.0)
    timed.submit(old), timed.submit(new)
    dropped = timed.expire(now=9.0)
    assert dropped == [old] and old.status == EXPIRED
    assert timed.schedule(4, 9.0) == [new]


def test_percentile_helper():
    assert percentile([], 50) is None
    assert percentile([3.0], 95) == 3.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
    assert percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0


# -- prefill fast path ------------------------------------------------------


def _shared_prefix_prompts(rng, cfg, prefix_len, suffix_lens):
    """Prompts sharing one random ``prefix_len``-token header, with random
    suffixes of the given lengths — the system-prompt workload shape."""
    prefix = [
        int(t)
        for t in np.asarray(
            jax.random.randint(rng, (prefix_len,), 1, cfg.vocab_size)
        )
    ]
    prompts = []
    for i, n in enumerate(suffix_lens):
        sfx = np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 100 + i), (n,), 1, cfg.vocab_size
            )
        )
        prompts.append(prefix + [int(t) for t in sfx])
    return prompts


def _greedy_refs(model, params, prompts, n_new):
    return [
        np.asarray(
            generate(
                model, params, jnp.asarray(p, jnp.int32)[None, :],
                max_new_tokens=n_new,
            )
        )[0]
        for p in prompts
    ]


def test_padded_prefill_inputs_helper():
    pos, last = padded_prefill_inputs([3, 5, 1], 5)
    np.testing.assert_array_equal(
        np.asarray(pos),
        [[0, 1, 2, -1, -1], [0, 1, 2, 3, 4], [0, -1, -1, -1, -1]],
    )
    np.testing.assert_array_equal(np.asarray(last), [2, 4, 0])


def test_default_prefill_buckets():
    assert default_prefill_buckets(1024) == (32, 64, 128, 256, 512, 1024)
    assert default_prefill_buckets(32) == (32,)
    assert default_prefill_buckets(100) == (32, 64, 100)


def test_bucketed_prefill_parity_staggered(rng):
    """Acceptance: bucketed + batched prefill is token-identical to exact
    prefill, INCLUDING staggered arrivals into reused slots — mixed prompt
    lengths through a 2-slot pool, every request vs its own static greedy
    reference."""
    cfg, model, _, params = _build(rng)
    lens, budgets = [3, 9, 6, 14, 11], [6, 4, 8, 5, 6]
    rows = [
        jax.random.randint(
            jax.random.fold_in(rng, i), (1, L), 1, cfg.vocab_size
        )
        for i, L in enumerate(lens)
    ]
    prompts = [[int(t) for t in np.asarray(r)[0]] for r in rows]
    refs = [
        np.asarray(
            generate(model, params, r, max_new_tokens=n)
        )[0]
        for r, n in zip(rows, budgets)
    ]
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(4, 8, 16),
    )
    outs = [eng.add_request(_req(prompts[0], budgets[0]))]
    outs.append(eng.add_request(_req(prompts[1], budgets[1])))
    eng.step(), eng.step()
    outs.append(eng.add_request(_req(prompts[2], budgets[2])))
    eng.step()
    outs.append(eng.add_request(_req(prompts[3], budgets[3])))
    outs.append(eng.add_request(_req(prompts[4], budgets[4])))
    eng.run()
    for i, (out, ref) in enumerate(zip(outs, refs)):
        assert out.status == FINISHED, f"request {i}: {out.status}"
        np.testing.assert_array_equal(
            np.asarray(out.tokens), ref, err_msg=f"request {i}"
        )
    assert eng.metrics.finished == 5 and eng.pool.n_free == 2
    # 5 distinct lengths collapsed onto <= 4 call shapes (3 buckets +
    # seq_len appended)
    assert eng.prefill_compiles <= 4


@pytest.mark.parametrize("chunk", [3, 5])
def test_chunked_prefill_parity(rng, chunk):
    """Acceptance: chunked prefill (prompts split across decode ticks,
    continuing into the slot's cache via multi-token write_index) is
    token-identical to exact monolithic prefill for every chunk budget."""
    cfg, model, _, params = _build(rng)
    lens = [9, 13, 4]
    rows = [
        jax.random.randint(
            jax.random.fold_in(rng, 10 + i), (1, L), 1, cfg.vocab_size
        )
        for i, L in enumerate(lens)
    ]
    prompts = [[int(t) for t in np.asarray(r)[0]] for r in rows]
    refs = [
        np.asarray(generate(model, params, r, max_new_tokens=6))[0]
        for r in rows
    ]
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(4, 8, 16),
        prefill_chunk_tokens=chunk,
    )
    outs = [eng.add_request(_req(p, 6)) for p in prompts]
    eng.run()
    for i, (out, ref) in enumerate(zip(outs, refs)):
        assert out.status == FINISHED, f"request {i}: {out.status}"
        np.testing.assert_array_equal(
            np.asarray(out.tokens), ref, err_msg=f"request {i}"
        )
    # the long prompts really went through chunk continuations
    assert eng.metrics.prefill_chunks >= sum(
        -(-L // chunk) for L in lens if L > chunk
    )


def test_chunked_prefill_interleaves_decode(rng):
    """A long prompt's chunks ride separate ticks, and already-running
    requests keep producing tokens on those ticks (the head-of-line fix)."""
    cfg, model, _, params = _build(rng)
    short = [int(t) for t in np.asarray(
        jax.random.randint(rng, (3,), 1, cfg.vocab_size)
    )]
    long = [int(t) for t in np.asarray(
        jax.random.randint(jax.random.fold_in(rng, 1), (12,), 1,
                           cfg.vocab_size)
    )]
    eng = ServingEngine(
        model, params, n_slots=2,
        prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
        decode_steps_per_tick=1,  # per-tick progress accounting below
    )
    a = eng.add_request(_req(short, 10))
    eng.step()  # a running
    b = eng.add_request(_req(long, 4))
    n_before = len(a.tokens)
    eng.step()  # b's first chunk + a's decode tick
    eng.step()  # b's second chunk + a's decode tick
    assert len(b.tokens) == 0  # still prefilling (12 tokens / 4-chunks)
    assert len(a.tokens) >= n_before + 2  # decode never stalled
    eng.run()
    ref_b = np.asarray(
        generate(model, params, jnp.asarray(long, jnp.int32)[None, :],
                 max_new_tokens=4)
    )[0]
    np.testing.assert_array_equal(np.asarray(b.tokens), ref_b)


def test_prefix_cache_unit():
    """PrefixCache mechanics: bucket-aligned lookup, every-prefix store,
    LRU eviction, hit/miss counters."""
    pc = PrefixCache(max_entries=2)
    buckets = (4, 8)
    assert pc.lookup([1, 2, 3, 4, 5], buckets) is None  # miss, empty
    stored = pc.store([1, 2, 3, 4, 5], buckets, "rowA")
    assert stored == [4]  # 8 >= len-? only the 4-prefix is proper
    hit = pc.lookup([1, 2, 3, 4, 9], buckets)
    assert hit == ("rowA", 4)
    assert (pc.hits, pc.misses) == (1, 1)
    # identical full prompt: the 4-prefix still serves (strictly shorter)
    assert pc.lookup([1, 2, 3, 4, 5], buckets) == ("rowA", 4)
    # a long prompt stores BOTH aligned prefixes, evicting LRU beyond 2
    pc.store(list(range(10, 19)), buckets, "rowB")
    assert len(pc) == 2 and pc.evictions == 1
    assert pc.lookup([1, 2, 3, 4, 9], buckets) is None  # evicted
    assert pc.lookup(list(range(10, 19)), buckets) == ("rowB", 8)
    with pytest.raises(ValueError):
        PrefixCache(0)


def test_prefix_reuse_exact_output(rng):
    """Acceptance: prefix-cache hits (copied K/V rows + remainder-only
    prefill) produce token-identical greedy output, across staggered
    arrivals into REUSED slots; counters and eviction behave."""
    cfg, model, _, params = _build(rng)
    prompts = _shared_prefix_prompts(
        rng, cfg, prefix_len=8, suffix_lens=[3, 6, 2, 9, 5]
    )
    refs = _greedy_refs(model, params, prompts, 6)
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(8, 16), prefix_cache_size=4,
    )
    outs = [eng.add_request(_req(prompts[0], 6))]
    outs.append(eng.add_request(_req(prompts[1], 6)))
    eng.step(), eng.step()
    for p in prompts[2:]:
        outs.append(eng.add_request(_req(p, 6)))
    eng.run()
    for i, (out, ref) in enumerate(zip(outs, refs)):
        assert out.status == FINISHED, f"request {i}: {out.status}"
        np.testing.assert_array_equal(
            np.asarray(out.tokens), ref, err_msg=f"request {i}"
        )
    s = eng.metrics.summary()
    # every request after the first shares the 8-token header
    assert s["prefix_hits"] >= 3 and s["prefix_hit_rate"] > 0.5


def test_prefix_reuse_int8_cache_exact(rng):
    """Acceptance: prefix reuse + bucketing over an int8 KV cache —
    copied quantized rows are bit-identical, greedy output matches the
    static int8 reference."""
    cfg, model, _, params = _build(rng, kv_cache_dtype="int8")
    prompts = _shared_prefix_prompts(
        rng, cfg, prefix_len=8, suffix_lens=[3, 5, 4, 6]
    )
    refs = _greedy_refs(model, params, prompts, 6)
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(8, 16), prefix_cache_size=2,
    )
    outs = [eng.add_request(_req(p, 6)) for p in prompts]
    eng.run()
    for i, (out, ref) in enumerate(zip(outs, refs)):
        np.testing.assert_array_equal(
            np.asarray(out.tokens), ref, err_msg=f"request {i}"
        )
    assert eng.metrics.prefix_hits >= 2


def test_prefill_compile_count(rng):
    """Acceptance: with bucketing, the prefill jit compiles at most one
    program per bucket regardless of how many distinct prompt lengths
    arrive — inspected via the jitted function's lowering cache."""
    from tpu_parallel.serving import engine as engine_mod

    engine_mod._engine_fns.cache_clear()  # fresh jit fns for this model
    cfg, model, _, params = _build(rng)
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(4, 8, 16),
    )
    if not hasattr(eng._prefill_fn, "_cache_size"):
        pytest.skip("jax.jit cache inspection unavailable")
    lengths = [3, 4, 5, 6, 7, 9, 11, 13, 15, 17]  # 10 distinct lengths
    for i, L in enumerate(lengths):
        p = jax.random.randint(
            jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
        )
        eng.add_request(_req(np.asarray(p), 2))
    eng.run()
    n_buckets = 4  # (4, 8, 16) + seq_len=32 appended
    assert eng._prefill_fn._cache_size() <= n_buckets
    assert eng.prefill_compiles <= n_buckets
    assert eng.metrics.finished == len(lengths)
    # same-bucket admissions batched: fewer device calls than requests
    assert eng.metrics.prefill_calls < len(lengths)
    # the legacy exact path really does compile per distinct length
    engine_mod._engine_fns.cache_clear()
    exact = ServingEngine(
        model, params, n_slots=4, prefill_buckets=None,
    )
    for i, L in enumerate([3, 5, 7, 9]):
        p = jax.random.randint(
            jax.random.fold_in(rng, 50 + i), (L,), 1, cfg.vocab_size
        )
        exact.add_request(_req(np.asarray(p), 2))
    exact.run()
    assert exact._prefill_fn._cache_size() == 4


def test_engine_refuses_relative_positional(rng):
    """The shared T5 bias table assumes row-uniform positions — a slot
    pool's mixed-depth rows (and padded prefill rows) break it, so the
    engine refuses loudly instead of serving row-0 bias to every slot."""
    cfg, model, _, params = _build(rng, positional="relative")
    with pytest.raises(NotImplementedError, match="relative"):
        ServingEngine(model, params, n_slots=2)


def test_scheduler_injectable_clock():
    """Satellite: the scheduler's own clock drives expire()/schedule()
    when ``now`` is omitted — timeout tests advance a fake clock instead
    of sleeping."""
    t = [0.0]
    sched = FIFOScheduler(SchedulerConfig(max_wait=5.0), clock=lambda: t[0])
    old = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    new = RequestOutput(Request(prompt=[1]), arrival_time=4.0)
    sched.submit(old), sched.submit(new)
    assert sched.expire() == []  # t=0: nothing stale
    t[0] = 6.0
    dropped = sched.expire()  # no `now` argument, no sleep
    assert dropped == [old] and old.status == EXPIRED
    assert sched.schedule(4) == [new]


def test_scheduler_bucket_grouping():
    """bucket_key constrains a tick's admissions to the FIFO head's
    group; other buckets keep their order for the next tick."""
    sched = FIFOScheduler(SchedulerConfig(max_prefills_per_tick=3))
    outs = [
        RequestOutput(Request(prompt=[1] * n), arrival_time=0.0)
        for n in [3, 9, 4, 2, 11]
    ]
    for out in outs:
        sched.submit(out)
    key = lambda o: len(o.request.prompt) <= 4  # two buckets
    first = sched.schedule(8, 0.0, bucket_key=key)
    assert first == [outs[0], outs[2], outs[3]]  # head's bucket, FIFO
    second = sched.schedule(8, 0.0, bucket_key=key)
    assert second == [outs[1], outs[4]]
    assert sched.depth == 0


def test_metrics_empty_run_summary():
    """Satellite: a run with ZERO finished requests still summarizes to
    serializable values (no IndexError/NaN in the JSONL sink)."""
    import json

    m = ServingMetrics()
    s = m.summary()
    assert s["finished"] == 0 and s["ttft_ms_p95"] is None
    assert s["prefix_hit_rate"] is None and s["tokens_per_sec"] is None
    json.dumps(s)  # must not raise
    m.record_tick(now=1.0, queue_depth=0, occupancy=0.0, new_tokens=0,
                  prefills=0, decoded=False)
    json.dumps(m.summary())
    assert percentile([None, None], 50) is None  # degenerate samples
    assert percentile([1.0], 200.0) == 1.0  # p clamped into [0, 100]


@pytest.mark.slow
def test_burst_ttft_improves_with_fast_path(rng):
    """Perf (wall-clock, >5s — slow lane): under an all-at-once burst of
    mixed-length shared-prefix prompts, the fast path (bucketed batched
    prefill + prefix reuse) cuts TTFT p95 vs the exact batch-1 engine.
    Timing-based: asserts direction with generous margin, not a ratio."""
    import time as _time

    cfg, model, _, params = _build(rng)
    prompts = _shared_prefix_prompts(
        rng, cfg, prefix_len=8,
        suffix_lens=[(i * 7) % 13 + 1 for i in range(24)],
    )

    def drive(**kw):
        eng = ServingEngine(
            model, params, n_slots=8,
            scheduler=SchedulerConfig(max_prefills_per_tick=4), **kw,
        )
        for p in prompts:  # warm compiles
            eng.add_request(_req(p, 2))
        eng.run()
        eng.reset_metrics()
        t0 = _time.perf_counter()
        outs = [eng.add_request(_req(p, 8)) for p in prompts]
        eng.run()
        assert all(out.status == FINISHED for out in outs)
        return eng.metrics.summary()

    slow = drive(prefill_buckets=None)
    fast = drive(prefill_buckets=(8, 16), prefix_cache_size=8)
    assert fast["prefix_hits"] > 0  # the prefix cache really engaged
    assert fast["ttft_ms_p95"] < slow["ttft_ms_p95"]


# -- speculative decoding ---------------------------------------------------


def test_spec_engine_greedy_parity_staggered(rng):
    """Acceptance: the speculative engine (n-gram drafter, adaptive K,
    bucketed prefill) is token-identical to the NON-spec engine and the
    static reference across staggered arrivals into reused slots."""
    cfg, model, _, params = _build(rng)
    lens, budgets = [3, 9, 6, 12, 5], [8, 6, 8, 5, 7]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=n,
        ))[0]
        for p, n in zip(prompts, budgets)
    ]

    def drive(**kw):
        eng = ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2),
            prefill_buckets=(4, 8, 16), **kw,
        )
        outs = [eng.add_request(_req(prompts[0], budgets[0]))]
        outs.append(eng.add_request(_req(prompts[1], budgets[1])))
        eng.step(), eng.step()
        outs.append(eng.add_request(_req(prompts[2], budgets[2])))
        eng.step()
        for p, n in zip(prompts[3:], budgets[3:]):
            outs.append(eng.add_request(_req(p, n)))
        eng.run()
        return eng, outs

    plain_eng, plain = drive()
    spec_eng, spec = drive(draft_tokens=3, spec_check_invariants=True)
    for i, (a, b, ref) in enumerate(zip(plain, spec, refs)):
        assert a.status == FINISHED and b.status == FINISHED
        np.testing.assert_array_equal(
            np.asarray(a.tokens), ref, err_msg=f"plain request {i}"
        )
        np.testing.assert_array_equal(
            np.asarray(b.tokens), ref, err_msg=f"spec request {i}"
        )
    s = spec_eng.metrics.summary()
    assert s["tokens_drafted"] > 0
    assert s["spec_acceptance_rate"] is not None


def test_spec_engine_int8_cache_parity(rng):
    """Speculative verify + int8 KV cache: quantization is per
    (position, kv-head), invisible to block width — spec greedy tokens
    equal the static int8 reference."""
    cfg, model, prompt, params = _build(rng, n_rows=2,
                                        kv_cache_dtype="int8")
    want = np.asarray(generate(model, params, prompt, max_new_tokens=8))
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        draft_tokens=3,
    )
    outs = [eng.add_request(_req(prompt[i], 8)) for i in range(2)]
    eng.run()
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(np.asarray(out.tokens), want[i])


def test_spec_engine_adversarial_drafter_exact(rng):
    """Acceptance: a drafter returning garbage every tick must cost only
    wasted verify positions — token-exact output, acceptance rate 0."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    want = np.asarray(generate(model, params, prompt, max_new_tokens=8))
    prompts = [[int(t) for t in np.asarray(prompt[i])] for i in range(2)]
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        draft_tokens=3, spec_adaptive=False,
        drafter=AntiOracleDrafter(_ref_map(prompts, want), cfg.vocab_size),
        spec_check_invariants=True,
    )
    outs = [eng.add_request(_req(p, 8)) for p in prompts]
    eng.run()
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(np.asarray(out.tokens), want[i])
    s = eng.metrics.summary()
    assert s["tokens_drafted"] > 0 and s["tokens_accepted"] == 0
    assert s["spec_acceptance_rate"] == 0.0
    assert s["spec_wasted_positions"] > 0


def test_spec_engine_eos_mid_verify_block(rng):
    """Acceptance: EOS landing INSIDE an accepted verify block truncates
    delivery at the EOS token and finishes with finish_reason="eos" —
    matching the non-spec engine on the same request."""
    cfg, model, prompt, params = _build(rng, n_rows=1, prompt_len=4)
    ref = list(np.asarray(
        generate(model, params, prompt, max_new_tokens=10)
    )[0])
    # an EOS value whose FIRST occurrence is deep enough that an oracle
    # K=6 block (emitted as ref[1..7] on the first verify tick) spans it
    eos_idx = next(
        i for i in range(2, 7) if ref[i] not in ref[:i]
    )
    eos = int(ref[eos_idx])
    prompts = [[int(t) for t in np.asarray(prompt[0])]]

    def drive(**kw):
        eng = ServingEngine(model, params, n_slots=1, **kw)
        out = eng.add_request(_req(prompts[0], 10, eos_token_id=eos))
        eng.run()
        return eng, out

    _, plain = drive()
    eng, spec = drive(
        draft_tokens=6, drafter=OracleDrafter(_ref_map(prompts, [ref])),
        spec_check_invariants=True,
    )
    assert plain.finish_reason == "eos" and spec.finish_reason == "eos"
    assert spec.tokens == ref[: eos_idx + 1] == plain.tokens
    # the oracle block really did span the EOS (some surplus discarded)
    assert eng.metrics.spec_wasted_positions > 0
    assert eng.pool.n_free == 1


def test_spec_engine_oracle_fewer_decode_ticks(rng):
    """The deterministic form of the speedup claim: with a perfect
    drafter the engine finishes the same workload in far fewer decode
    ticks than one-token-per-tick (no wall-clock in tier-1)."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    n_new = 12
    want = np.asarray(generate(model, params, prompt, max_new_tokens=n_new))
    prompts = [[int(t) for t in np.asarray(prompt[i])] for i in range(2)]

    def drive(**kw):
        eng = ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2), **kw,
        )
        outs = [eng.add_request(_req(p, n_new)) for p in prompts]
        eng.run()
        for i, out in enumerate(outs):
            np.testing.assert_array_equal(np.asarray(out.tokens), want[i])
        return eng.metrics

    plain = drive(decode_steps_per_tick=1)  # the per-step baseline
    spec = drive(
        draft_tokens=4, drafter=OracleDrafter(_ref_map(prompts, want)),
    )
    assert plain.decode_ticks == n_new - 1  # one token per tick
    assert spec.decode_ticks <= 3  # ~5 tokens per verify tick
    assert spec.tokens_accepted > 0
    s = spec.summary()
    assert s["tokens_per_decode_tick"] > plain.summary()[
        "tokens_per_decode_tick"
    ]


def test_spec_engine_per_request_knobs(rng):
    """Per-request draft_tokens: 0 opts a request out of drafting (it
    still shares verify ticks) while its neighbour speculates; both stay
    exact, and a hot-temperature request rides along unharmed."""
    cfg, model, prompt, params = _build(rng, n_rows=1)
    ref = np.asarray(generate(model, params, prompt, max_new_tokens=6))[0]
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=4),
        draft_tokens=3, rng=jax.random.PRNGKey(3),
    )
    on = eng.add_request(_req(prompt[0], 6))
    off = eng.add_request(_req(prompt[0], 6, draft_tokens=0))
    hot = eng.add_request(
        _req(prompt[0], 6, sampling=SamplingParams(temperature=4.0))
    )
    eng.run()
    np.testing.assert_array_equal(np.asarray(on.tokens), ref)
    np.testing.assert_array_equal(np.asarray(off.tokens), ref)
    assert len(hot.tokens) == 6
    assert all(0 <= tok < cfg.vocab_size for tok in hot.tokens)
    with pytest.raises(ValueError, match="draft_tokens"):
        Request(prompt=[1], draft_tokens=-1)


def test_spec_engine_chunked_prefill_interleaves(rng):
    """Speculative ticks and chunked prefill coexist: a long prompt's
    chunks still ride separate ticks while running requests keep
    producing (multi-token) output, and everything stays exact."""
    cfg, model, _, params = _build(rng)
    short = [int(t) for t in np.asarray(
        jax.random.randint(rng, (3,), 1, cfg.vocab_size)
    )]
    long = [int(t) for t in np.asarray(
        jax.random.randint(jax.random.fold_in(rng, 1), (12,), 1,
                           cfg.vocab_size)
    )]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=n,
        ))[0]
        for p, n in ((short, 10), (long, 4))
    ]
    eng = ServingEngine(
        model, params, n_slots=2,
        prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
        draft_tokens=3,
    )
    a = eng.add_request(_req(short, 10))
    eng.step()
    b = eng.add_request(_req(long, 4))
    n_before = len(a.tokens)
    eng.step(), eng.step()
    assert len(b.tokens) == 0  # still prefilling
    assert len(a.tokens) >= n_before + 2  # decode never stalled
    eng.run()
    np.testing.assert_array_equal(np.asarray(a.tokens), refs[0])
    np.testing.assert_array_equal(np.asarray(b.tokens), refs[1])


def test_cache_pool_slot_aligned_guard(rng):
    """The no-rollback invariant guard: aligned slots pass; a table made
    deliberately misaligned trips the assert."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    eng = ServingEngine(model, params, n_slots=2, draft_tokens=2)
    out = eng.add_request(_req(prompt[0], 4))
    eng.run()
    assert out.status == FINISHED
    eng.pool.assert_slot_aligned(0)
    eng.pool.assert_slot_aligned(1)

    def corrupt(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name.startswith("cached_pos"):
            return leaf.at[..., 0, 3].set(7)  # slot 0, column 3 -> pos 7
        return leaf

    eng.pool.cache = jax.tree_util.tree_map_with_path(corrupt, eng.pool.cache)
    with pytest.raises(AssertionError, match="misaligned"):
        eng.pool.assert_slot_aligned(0)


# -- fused multi-step decode tick -------------------------------------------


def _drive_engine(model, params, prompts, budgets, staggered=False, **kw):
    """Submit ``prompts`` (optionally staggered across ticks) and run to
    idle; returns (engine, outputs)."""
    eng = ServingEngine(
        model, params,
        scheduler=SchedulerConfig(max_prefills_per_tick=2), **kw,
    )
    outs = []
    if staggered:
        outs.append(eng.add_request(_req(prompts[0], budgets[0])))
        outs.append(eng.add_request(_req(prompts[1], budgets[1])))
        eng.step(), eng.step()
        outs.append(eng.add_request(_req(prompts[2], budgets[2])))
        eng.step()
        for p, n in zip(prompts[3:], budgets[3:]):
            outs.append(eng.add_request(_req(p, n)))
    else:
        outs = [
            eng.add_request(_req(p, n)) for p, n in zip(prompts, budgets)
        ]
    eng.run()
    return eng, outs


def test_fused_tick_greedy_parity_staggered(rng):
    """Acceptance: the fused tick (T=4) is BITWISE identical to the
    per-step engine across staggered arrivals into reused slots, with
    budgets deliberately not multiples of T so every request exhausts
    its budget MID-scan-block."""
    cfg, model, _, params = _build(rng)
    lens, budgets = [3, 9, 6, 12, 5], [6, 5, 9, 3, 7]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    kw = dict(n_slots=2, prefill_buckets=(4, 8, 16))
    plain_eng, plain = _drive_engine(
        model, params, prompts, budgets, staggered=True,
        decode_steps_per_tick=1, **kw,
    )
    fused_eng, fused = _drive_engine(
        model, params, prompts, budgets, staggered=True,
        decode_steps_per_tick=4, **kw,
    )
    for i, (a, b) in enumerate(zip(plain, fused)):
        assert a.status == FINISHED and b.status == FINISHED
        assert a.finish_reason == b.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(b.tokens), np.asarray(a.tokens),
            err_msg=f"request {i}",
        )
    # the fused engine really amortized: far fewer decode ticks
    assert fused_eng.metrics.decode_ticks < plain_eng.metrics.decode_ticks
    assert fused_eng.pool.n_free == 2


def test_fused_tick_eos_mid_block(rng):
    """EOS sampled MID-scan-block: delivery truncates at the EOS token,
    the surplus scan steps park their writes, and the retired slot is
    clean for its next occupant — bitwise equal to the per-step engine."""
    cfg, model, prompt, params = _build(rng, n_rows=2, prompt_len=4)
    ref = list(np.asarray(
        generate(model, params, prompt[:1], max_new_tokens=12)
    )[0])
    # an EOS whose first occurrence is deep enough that a T=8 block
    # spans it mid-scan
    eos_idx = next(i for i in range(2, 7) if ref[i] not in ref[:i])
    eos = int(ref[eos_idx])
    prompts = [[int(t) for t in np.asarray(prompt[0])]]

    def drive(**kw):
        eng = ServingEngine(model, params, n_slots=1, **kw)
        out = eng.add_request(_req(prompts[0], 12, eos_token_id=eos))
        eng.run()
        # the slot is reusable and unpolluted after the mid-block retire
        nxt = eng.add_request(_req(prompts[0], 4))
        eng.run()
        return eng, out, nxt

    _, plain, plain_next = drive(decode_steps_per_tick=1)
    eng, fused, fused_next = drive(decode_steps_per_tick=8)
    assert plain.finish_reason == fused.finish_reason == "eos"
    assert fused.tokens == ref[: eos_idx + 1] == plain.tokens
    assert fused_next.tokens == plain_next.tokens
    assert eng.pool.n_free == 1
    eng.pool.assert_slot_aligned(0)


def test_fused_tick_int8_parity(rng):
    """Fused tick over an int8 KV cache (the int8-native attention read):
    bitwise equal to the per-step int8 engine and the static int8
    reference."""
    cfg, model, prompt, params = _build(rng, n_rows=2, kv_cache_dtype="int8")
    want = np.asarray(generate(model, params, prompt, max_new_tokens=9))
    prompts = [[int(t) for t in np.asarray(prompt[i])] for i in range(2)]
    _, plain = _drive_engine(
        model, params, prompts, [9, 9], n_slots=2, decode_steps_per_tick=1,
    )
    _, fused = _drive_engine(
        model, params, prompts, [9, 9], n_slots=2, decode_steps_per_tick=4,
    )
    for i in range(2):
        np.testing.assert_array_equal(np.asarray(plain[i].tokens), want[i])
        np.testing.assert_array_equal(np.asarray(fused[i].tokens), want[i])


def test_fused_tick_chunked_prefill_interleave_parity(rng):
    """Fused decode ticks compose with chunked prefill: a long prompt's
    chunks keep riding one-per-tick while fused blocks advance running
    requests; both requests stay bitwise exact."""
    cfg, model, _, params = _build(rng)
    short = [int(t) for t in np.asarray(
        jax.random.randint(rng, (3,), 1, cfg.vocab_size)
    )]
    long = [int(t) for t in np.asarray(
        jax.random.randint(jax.random.fold_in(rng, 1), (12,), 1,
                           cfg.vocab_size)
    )]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=n,
        ))[0]
        for p, n in ((short, 11), (long, 5))
    ]
    eng = ServingEngine(
        model, params, n_slots=2,
        prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
        decode_steps_per_tick=4,
    )
    a = eng.add_request(_req(short, 11))
    eng.step()
    b = eng.add_request(_req(long, 5))
    eng.run()
    np.testing.assert_array_equal(np.asarray(a.tokens), refs[0])
    np.testing.assert_array_equal(np.asarray(b.tokens), refs[1])
    assert eng.metrics.prefill_chunks >= 3  # 12 tokens / 4-chunks


def test_fused_tick_donation_invalidates_old_buffers(rng):
    """Satellite (buffer-donation audit): the cache pool AND the device
    slot-state operands are DONATED — after a tick the previous tick's
    buffers are deleted, so no second pool copy can exist.  Pinned for
    the fused tick and the per-step ``_decode_fn`` alike; a stale
    reference held across a tick raises on use."""
    cfg, model, prompt, params = _build(rng, n_rows=1)
    for steps in (1, 4):
        eng = ServingEngine(
            model, params, n_slots=2, decode_steps_per_tick=steps,
        )
        out = eng.add_request(_req(prompt[0], 12))
        eng.step()  # admit + first decode tick
        old_cache = jax.tree_util.tree_leaves(eng.pool.cache)
        old_state = (
            jax.tree_util.tree_leaves(eng._dev_state) if steps > 1 else []
        )
        eng.step()  # decode-only tick: donates cache (and fused state)
        assert all(leaf.is_deleted() for leaf in old_cache), (
            f"T={steps}: old pool buffers survived the tick (donation "
            "regressed — a second full pool copy is alive)"
        )
        assert all(leaf.is_deleted() for leaf in old_state)
        eng.run()
        assert out.status == FINISHED and len(out.tokens) == 12


def test_fused_tick_compile_count_pin(rng):
    """The fused tick compiles ONCE: its state/cache shapes are fixed by
    (n_slots, seq_len), so a mixed workload — staggered arrivals, EOS,
    varying budgets, prefix hits — adds prefill shapes only, bounded by
    the bucket set (+1 extend shape per distinct hit group width)."""
    from tpu_parallel.serving import engine as engine_mod

    engine_mod._engine_fns.cache_clear()
    engine_mod._fused_engine_fn.cache_clear()
    cfg, model, _, params = _build(rng)
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(4, 8, 16), prefix_cache_size=2,
        decode_steps_per_tick=8,
    )
    if not hasattr(eng._fused_fn, "_cache_size"):
        pytest.skip("jax.jit cache inspection unavailable")
    shared = [7, 3, 5, 2]
    lengths = [3, 4, 5, 6, 9, 11, 15]
    for i, L in enumerate(lengths):
        sfx = jax.random.randint(
            jax.random.fold_in(rng, i), (max(1, L - 4),), 1, cfg.vocab_size
        )
        p = shared + [int(t) for t in np.asarray(sfx)]
        eng.add_request(_req(p, 2 + (i % 5)))
        if i % 2:
            eng.step()
    eng.run()
    assert eng.metrics.finished == len(lengths)
    n_buckets = 4  # (4, 8, 16) + seq_len appended
    assert eng._fused_fn._cache_size() == 1  # ONE fused program, ever
    assert eng._prefill_fn._cache_size() <= n_buckets
    # total jitted decode+prefill+extend shapes stay <= #buckets + 2
    assert (
        eng._fused_fn._cache_size()
        + eng._prefill_fn._cache_size()
        + eng._extend_fn._cache_size()
    ) <= n_buckets + 2


def test_fused_tick_dispatch_metrics(rng):
    """Satellite (dispatch observability): host_dispatches /
    tokens_per_dispatch / host_ms_per_tick flow registry -> summary ->
    Prometheus text, and the fused tick's amortization is visible —
    tokens per dispatch strictly above the per-step engine's."""
    from tpu_parallel.obs import write_prometheus

    cfg, model, prompt, params = _build(rng, n_rows=1)
    prompts = [[int(t) for t in np.asarray(prompt[0])]]

    def drive(steps):
        eng, _ = _drive_engine(
            model, params, prompts, [12], n_slots=1,
            decode_steps_per_tick=steps,
        )
        return eng

    plain, fused = drive(1), drive(8)
    for eng in (plain, fused):
        s = eng.metrics.summary()
        assert s["host_dispatches"] == eng.metrics.host_dispatches > 0
        assert s["tokens_per_dispatch_mean"] > 0
        assert s["host_ms_per_tick_p95"] is not None
    assert (
        fused.metrics.summary()["tokens_per_dispatch_mean"]
        > plain.metrics.summary()["tokens_per_dispatch_mean"]
    )
    # far fewer host round-trips for the same 12 tokens
    assert fused.metrics.host_dispatches < plain.metrics.host_dispatches
    text = write_prometheus(fused.registry, "/tmp/test_dispatch_prom.txt")
    exposition = open(text).read()
    for name in (
        "serving_host_dispatches_total",
        "serving_tokens_per_dispatch",
        "serving_host_ms_per_tick",
    ):
        assert name in exposition, name


def test_fused_tick_cancel_from_stream_callback(rng):
    """Regression: cancel() issued from inside an on_token stream
    callback (the client-disconnect pattern) mid-fused-block must drop
    the slot's surplus device tokens and leave neighbours delivering —
    not crash the tick on the released slot's None record."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    ref = np.asarray(generate(model, params, prompt[1:2], max_new_tokens=12))
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        decode_steps_per_tick=8,
    )
    got = []

    def disconnect(ev):
        got.append(ev.token)
        if len(got) == 3:  # mid-block: 8-token device blocks
            assert eng.cancel(victim.request.request_id)

    victim = eng.add_request(
        _req(prompt[0], 12, on_token=disconnect)
    )
    neighbour = eng.add_request(_req(prompt[1], 12))
    eng.run()
    assert victim.status == "cancelled"
    assert len(victim.tokens) == 3  # surplus block tokens dropped
    assert neighbour.status == FINISHED
    np.testing.assert_array_equal(np.asarray(neighbour.tokens), ref[0])
    assert eng.pool.n_free == 2
    # a callback cancelling a DIFFERENT slot mid-loop is survived too
    eng2 = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        decode_steps_per_tick=8,
    )
    outs = {}

    def shoot_other(ev):
        other = outs.get("b")
        if other is not None and not other.done:
            eng2.cancel(other.request.request_id)

    outs["a"] = eng2.add_request(_req(prompt[0], 12, on_token=shoot_other))
    outs["b"] = eng2.add_request(_req(prompt[1], 12))
    eng2.run()
    assert outs["a"].status == FINISHED
    assert outs["b"].status == "cancelled"
    assert eng2.pool.n_free == 2
    # ... and on the SPECULATIVE per-step tick (same cancel-mid-loop class)
    eng3 = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2), draft_tokens=3,
    )
    souts = {}

    def spec_shoot(ev):
        other = souts.get("b")
        if other is not None and not other.done:
            eng3.cancel(other.request.request_id)

    souts["a"] = eng3.add_request(_req(prompt[0], 12, on_token=spec_shoot))
    souts["b"] = eng3.add_request(_req(prompt[1], 12))
    eng3.run()
    assert souts["a"].status == FINISHED
    assert souts["b"].status == "cancelled"
    assert eng3.pool.n_free == 2


def test_fused_tick_knob_validation(rng):
    """decode_steps_per_tick < 1 refuses; explicit T > 1 with a CUSTOM
    drafter refuses (in-scan drafting can only mirror the traceable
    NGram drafter), while the default drafter fuses T verify blocks per
    dispatch; 'auto' resolves to 8 plain and 1 speculative; unified_tick
    needs a fused tick."""
    cfg, model, _, params = _build(rng)
    with pytest.raises(ValueError, match="decode_steps_per_tick"):
        ServingEngine(model, params, n_slots=1, decode_steps_per_tick=0)
    with pytest.raises(NotImplementedError, match="drafter"):
        ServingEngine(
            model, params, n_slots=1, decode_steps_per_tick=4,
            draft_tokens=2, drafter=OracleDrafter({}),
        )
    spec_fused = ServingEngine(
        model, params, n_slots=1, decode_steps_per_tick=4, draft_tokens=2,
    )
    assert spec_fused.decode_steps_per_tick == 4
    assert spec_fused._spec_fused_fn is not None
    assert ServingEngine(model, params, n_slots=1).decode_steps_per_tick == 8
    assert (
        ServingEngine(
            model, params, n_slots=1, draft_tokens=2
        ).decode_steps_per_tick
        == 1
    )
    with pytest.raises(ValueError, match="unified_tick"):
        ServingEngine(
            model, params, n_slots=1, decode_steps_per_tick=1,
            unified_tick=True,
        )
    assert ServingEngine(model, params, n_slots=1).unified_tick
    assert not ServingEngine(
        model, params, n_slots=1, unified_tick=False
    ).unified_tick


# -- the unified ragged tick (prefill+decode in one dispatch) ---------------


def _drive_interleaved(model, params, prompts, budgets, **kw):
    """Submit prompts staggered so chunked prefills interleave running
    decodes, run to idle; returns (engine, outputs)."""
    eng = ServingEngine(
        model, params,
        scheduler=SchedulerConfig(max_prefills_per_tick=2), **kw,
    )
    outs = [eng.add_request(_req(prompts[0], budgets[0]))]
    eng.step()
    for p, n in zip(prompts[1:], budgets[1:]):
        outs.append(eng.add_request(_req(p, n)))
        eng.step()
    eng.run()
    return eng, outs


@pytest.mark.parametrize(
    "variant", ["plain", "int8", "paged", "paged_prefix"]
)
def test_unified_tick_bitwise_vs_per_phase(rng, variant):
    """Acceptance (tentpole): the unified ragged tick — chunked prefills
    and fused decode in ONE dispatch per tick, with in-device
    final-chunk activation — is BITWISE identical to the per-phase
    engine (unified_tick=False: per-slot chunk extends, then the decode
    dispatch) across staggered arrivals, chunk+decode interleave and
    slot reuse; per layout (fixed / int8 / paged / paged+prefix-cache)."""
    overrides = {"int8": dict(kv_cache_dtype="int8")}.get(variant, {})
    cfg, model, _, params = _build(rng, **overrides)
    layout = {
        "paged": dict(kv_block_tokens="auto"),
        "paged_prefix": dict(kv_block_tokens="auto", prefix_cache_size=2),
    }.get(variant, {})
    lens, budgets = [3, 12, 9, 14, 5], [9, 5, 7, 4, 6]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 20 + i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    kw = dict(
        n_slots=2, prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
        decode_steps_per_tick=4, **layout,
    )
    phase_eng, phased = _drive_interleaved(
        model, params, prompts, budgets, unified_tick=False, **kw
    )
    uni_eng, unified = _drive_interleaved(
        model, params, prompts, budgets, unified_tick=True, **kw
    )
    assert uni_eng.unified_tick and not phase_eng.unified_tick
    for i, (a, b) in enumerate(zip(phased, unified)):
        assert a.status == FINISHED and b.status == FINISHED, (
            f"request {i}: {a.status} / {b.status}"
        )
        np.testing.assert_array_equal(
            np.asarray(b.tokens), np.asarray(a.tokens),
            err_msg=f"request {i} ({variant})",
        )
    # both really chunked; the unified engine paid FEWER device
    # dispatches for the same tokens (chunk extends rode the decode
    # dispatch) — the tick's raison d'etre
    assert uni_eng.metrics.prefill_chunks >= 3
    assert phase_eng.metrics.prefill_chunks == uni_eng.metrics.prefill_chunks
    assert uni_eng.metrics.host_dispatches < phase_eng.metrics.host_dispatches
    assert uni_eng.pool.n_free == 2


def test_unified_tick_chunk_only_progress_regression(rng):
    """Satellite bugfix: a tick holding ONLY mid-chunk prefill rows (no
    decode-live slots) makes progress by chunk advancement alone — the
    no-progress RuntimeError guard must not fire on it.  Pinned by
    stepping a single long chunked prompt through an otherwise-idle
    unified engine, tick by tick."""
    cfg, model, _, params = _build(rng)
    long = [int(t) for t in np.asarray(
        jax.random.randint(rng, (14,), 1, cfg.vocab_size)
    )]
    ref = np.asarray(generate(
        model, params, jnp.asarray(long, jnp.int32)[None, :],
        max_new_tokens=4,
    ))[0]
    eng = ServingEngine(
        model, params, n_slots=1, prefill_buckets=(4, 8, 16),
        prefill_chunk_tokens=4, decode_steps_per_tick=8,
    )
    assert eng.unified_tick
    out = eng.add_request(_req(long, 4))
    # ticks 1..3 hold only the mid-chunk prefill row: every one must
    # advance the chunk (not raise, not spin) and deliver nothing
    for tick in range(3):
        events = eng.step()
        assert events == [], f"tick {tick} delivered early: {events}"
        assert len(out.tokens) == 0
    assert eng.metrics.prefill_chunks == 3
    eng.run()
    assert out.status == FINISHED
    np.testing.assert_array_equal(np.asarray(out.tokens), ref)


def test_unified_tick_eos_at_activation_and_mid_block(rng):
    """EOS discipline through the unified tick: an EOS that IS the
    in-device-sampled first token retires the slot before it ever
    decodes, and an EOS mid-decode-block truncates delivery — both
    bitwise equal to the per-phase engine, slot clean for reuse."""
    cfg, model, _, params = _build(rng)
    long = [int(t) for t in np.asarray(
        jax.random.randint(rng, (11,), 1, cfg.vocab_size)
    )]
    ref = list(np.asarray(generate(
        model, params, jnp.asarray(long, jnp.int32)[None, :],
        max_new_tokens=12,
    ))[0])
    kw = dict(
        n_slots=1, prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
        decode_steps_per_tick=8,
    )

    def drive(eos, unified):
        eng = ServingEngine(model, params, unified_tick=unified, **kw)
        out = eng.add_request(_req(long, 12, eos_token_id=eos))
        eng.run()
        nxt = eng.add_request(_req(long, 3))
        eng.run()
        assert eng.pool.n_free == 1
        return out, nxt

    # eos_idx 0: the EOS IS the in-device-sampled activation token (the
    # request retires without ever decoding); eos_idx 3: EOS lands
    # mid-decode-block (both engines stop at that token's FIRST greedy
    # occurrence — wherever it is, they must agree bitwise)
    for eos_idx in (0, 3):
        eos = int(ref[eos_idx])
        a, a_next = drive(eos, unified=False)
        b, b_next = drive(eos, unified=True)
        assert a.finish_reason == b.finish_reason == "eos"
        assert b.tokens == a.tokens and b.tokens[-1] == eos
        assert b_next.tokens == a_next.tokens


def test_unified_tick_chunk_starts_batch(rng):
    """Scheduler satellite: under the unified tick, chunked prompts
    share ONE admission group — two long prompts admit the SAME tick
    (each claiming a slot, both riding the one [n_slots, chunk_tokens]
    dispatch) instead of serializing one admission per tick; outputs
    stay bitwise."""
    cfg, model, _, params = _build(rng)
    longs = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 40 + i), (11 + i,), 1,
                cfg.vocab_size
            )
        )]
        for i in range(2)
    ]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=5,
        ))[0]
        for p in longs
    ]
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
        decode_steps_per_tick=4,
    )
    outs = [eng.add_request(_req(p, 5)) for p in longs]
    eng.step()
    # both admitted (and mid-chunk) after ONE tick
    assert eng.in_flight == 2 and eng.scheduler.depth == 0
    eng.run()
    for out, ref in zip(outs, refs):
        assert out.status == FINISHED
        np.testing.assert_array_equal(np.asarray(out.tokens), ref)


@pytest.mark.parametrize("variant", ["plain", "int8", "paged"])
def test_spec_fused_tick_bitwise(rng, variant):
    """Fused speculative verify: T draft-verify-accept blocks per
    dispatch with in-scan NGram drafting — bitwise identical to the
    per-step spec engine AND the static reference across staggered
    arrivals, budgets exhausting mid-block, per layout."""
    overrides = {"int8": dict(kv_cache_dtype="int8")}.get(variant, {})
    cfg, model, _, params = _build(rng, **overrides)
    layout = (
        dict(kv_block_tokens="auto") if variant == "paged" else {}
    )
    lens, budgets = [3, 9, 6, 12, 5], [6, 5, 9, 3, 7]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 60 + i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    kw = dict(
        n_slots=2, prefill_buckets=(4, 8, 16), draft_tokens=3, **layout
    )
    step_eng, stepped = _drive_engine(
        model, params, prompts, budgets, staggered=True,
        decode_steps_per_tick=1, **kw,
    )
    fused_eng, fused = _drive_engine(
        model, params, prompts, budgets, staggered=True,
        decode_steps_per_tick=4, **kw,
    )
    for i, (a, b) in enumerate(zip(stepped, fused)):
        assert a.status == FINISHED and b.status == FINISHED
        np.testing.assert_array_equal(
            np.asarray(b.tokens), np.asarray(a.tokens),
            err_msg=f"request {i} ({variant})",
        )
    # the fused spec engine really amortized its verify dispatches
    assert fused_eng.metrics.host_dispatches < step_eng.metrics.host_dispatches
    # and both drafted (the drafter twin really ran in-scan)
    assert fused_eng.metrics.tokens_drafted > 0
    assert fused_eng.pool.n_free == 2


def test_spec_fused_eos_mid_block_and_chunked(rng):
    """Fused spec composes with chunked prefill (the unified spec tick)
    and truncates at EOS mid-verify-block — bitwise vs the per-step
    spec engine."""
    cfg, model, prompt, params = _build(rng, n_rows=1, prompt_len=4)
    ref = list(np.asarray(
        generate(model, params, prompt[:1], max_new_tokens=12)
    )[0])
    eos_idx = next(i for i in range(2, 7) if ref[i] not in ref[:i])
    eos = int(ref[eos_idx])
    short = [int(t) for t in np.asarray(prompt[0])]
    long = [int(t) for t in np.asarray(
        jax.random.randint(jax.random.fold_in(rng, 3), (12,), 1,
                           cfg.vocab_size)
    )]

    def drive(steps):
        eng = ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2),
            prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
            draft_tokens=3, decode_steps_per_tick=steps,
        )
        a = eng.add_request(_req(short, 12, eos_token_id=eos))
        eng.step()
        b = eng.add_request(_req(long, 5))
        eng.run()
        return a, b

    a1, b1 = drive(1)
    a4, b4 = drive(4)
    assert a1.finish_reason == a4.finish_reason == "eos"
    assert a4.tokens == a1.tokens == ref[: eos_idx + 1]
    assert b4.tokens == b1.tokens and b1.status == FINISHED


def test_unified_tick_compile_count_pin(rng):
    """Jit compile-count pin: the unified fn compiles ONCE (its chunk
    and state shapes are fixed by (n_slots, chunk_tokens, seq_len)), so
    a mixed chunked workload adds the ONE unified program on top of the
    fused-tick family — the compile-shape family stays O(#buckets + 1)."""
    from tpu_parallel.serving import engine as engine_mod

    engine_mod._engine_fns.cache_clear()
    engine_mod._fused_engine_fn.cache_clear()
    engine_mod._unified_engine_fn.cache_clear()
    cfg, model, _, params = _build(rng)
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
        decode_steps_per_tick=8,
    )
    if not hasattr(eng._unified_fn, "_cache_size"):
        pytest.skip("jax.jit cache inspection unavailable")
    lengths = [3, 5, 9, 11, 14, 6, 13]
    for i, L in enumerate(lengths):
        p = [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 80 + i), (L,), 1, cfg.vocab_size
            )
        )]
        eng.add_request(_req(p, 2 + (i % 5)))
        if i % 2:
            eng.step()
    eng.run()
    assert eng.metrics.finished == len(lengths)
    assert eng._unified_fn._cache_size() == 1  # ONE unified program, ever
    assert eng._fused_fn._cache_size() == 1


def test_run_overlap_bitwise_and_donation_audit(rng):
    """Double-buffered host/device overlap: run(overlap=True) launches
    tick N+1 before collecting tick N on pure-decode stretches — output
    BITWISE identical to the sequential loop, measured overlap ratio
    > 0, and the donation audit: after a launch the previous tick's
    state/cache buffers are deleted (donated into the in-flight
    dispatch), and the engine never reads the pending tick's donated
    buffers before collect (a read would raise on the deleted buffer)."""
    cfg, model, _, params = _build(rng)
    lens, budgets = [3, 5, 9, 4], [12, 9, 11, 10]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 90 + i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    for layout in ({}, {"kv_block_tokens": "auto"}):
        seq_eng = ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2), **layout,
        )
        seq = [
            seq_eng.add_request(_req(p, n))
            for p, n in zip(prompts, budgets)
        ]
        seq_eng.run()
        ov_eng = ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2), **layout,
        )
        ov = [
            ov_eng.add_request(_req(p, n))
            for p, n in zip(prompts, budgets)
        ]
        ov_eng.run(overlap=True)
        for i, (a, b) in enumerate(zip(seq, ov)):
            assert a.status == FINISHED and b.status == FINISHED
            np.testing.assert_array_equal(
                np.asarray(b.tokens), np.asarray(a.tokens),
                err_msg=f"request {i} ({layout})",
            )
        s = ov_eng.metrics.summary()
        assert s["host_overlap_ratio"] > 0, layout
        assert s["overlapped_dispatches"] > 0
        assert seq_eng.metrics.summary()["host_overlap_ratio"] == 0.0
    # donation audit on the pipelined pair: launch-ahead donates the
    # previous tick's state+cache into the in-flight dispatch
    eng = ServingEngine(model, params, n_slots=1)
    out = eng.add_request(_req(prompts[0], 28))
    eng.step()  # admit + first fused tick (clean state now)
    assert eng._can_launch_ahead()
    p1 = eng.launch()
    old_state = jax.tree_util.tree_leaves(eng._dev_state)
    old_cache = jax.tree_util.tree_leaves(eng.pool.cache)
    assert eng._can_launch_ahead()
    p2 = eng.launch(ahead=True)  # donates p1's returned buffers
    assert all(leaf.is_deleted() for leaf in old_state), (
        "launch-ahead did not donate the pending tick's state buffers"
    )
    assert all(leaf.is_deleted() for leaf in old_cache)
    ev1 = eng.collect(p1)
    ev2 = eng.collect(p2)
    assert len(ev1) == len(ev2) == eng.decode_steps_per_tick
    eng.run()
    assert out.status == FINISHED and len(out.tokens) == 28


def test_run_overlap_finish_and_retire_in_flight(rng):
    """Overlap pipeline edge: requests FINISHING inside a pipelined tick
    retire cleanly — the overlapped surplus tick parks on the device
    live-mask, the host retires at collect, and the trailing pending
    tick is always collected (no hang, no stray tokens, slots free)."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    refs = [
        np.asarray(generate(
            model, params, prompt[i : i + 1], max_new_tokens=9
        ))[0]
        for i in range(2)
    ]
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        decode_steps_per_tick=4,
    )
    outs = [eng.add_request(_req(prompt[i], 9)) for i in range(2)]
    events = eng.run(overlap=True)
    for i, out in enumerate(outs):
        assert out.status == FINISHED and out.finish_reason == "length"
        np.testing.assert_array_equal(np.asarray(out.tokens), refs[i])
    assert eng.pool.n_free == 2 and not eng.has_work()
    # 9-token budgets on 4-step ticks: the finish lands mid-pipeline
    assert sum(1 for ev in events if ev.token >= 0) == 18


@pytest.mark.slow
def test_spec_engine_wall_clock_with_oracle(rng):
    """Perf (wall-clock — slow lane): with a high-acceptance drafter the
    speculative engine drains the same greedy workload faster than
    one-token-per-tick.  Direction only, generous margin."""
    import time as _time

    cfg, model, _, params = _build(rng, n_rows=8, prompt_len=5)
    n_new = 12
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (5,), 1, cfg.vocab_size
            )
        )]
        for i in range(8)
    ]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=n_new,
        ))[0]
        for p in prompts
    ]

    def drive(**kw):
        eng = ServingEngine(
            model, params, n_slots=8,
            scheduler=SchedulerConfig(max_prefills_per_tick=8), **kw,
        )
        for p in prompts:  # warm compiles
            eng.add_request(_req(p, 2))
        eng.run()
        t0 = _time.perf_counter()
        outs = [eng.add_request(_req(p, n_new)) for p in prompts]
        eng.run()
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(out.tokens), ref)
        return _time.perf_counter() - t0

    dt_plain = drive()
    dt_spec = drive(
        draft_tokens=4, drafter=OracleDrafter(_ref_map(prompts, refs)),
    )
    assert dt_spec < dt_plain


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax.shard_map unavailable (the repo's sharded paths need it)",
)
def test_engine_sharded_tp_matches_static(mesh_data4_model2, rng):
    """TP serving through the engine: mesh-sharded weights, head-sharded
    cache pool, greedy tokens identical to generate_sharded on the same
    mesh."""
    import flax.linen as nn
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.models.generate import generate_sharded

    mesh = mesh_data4_model2
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 5), 1, cfg.vocab_size)

    def init(r, p):
        return model.init({"params": r}, p, train=False)["params"]

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, prompt))
    params = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, prompt)

    want = np.asarray(
        generate_sharded(model, params, prompt, mesh, max_new_tokens=6)
    )
    eng = ServingEngine(
        model, params, n_slots=2, mesh=mesh, param_specs=specs,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
    )
    outs = [eng.add_request(_req(prompt[i], 6)) for i in range(2)]
    eng.run()
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(np.asarray(out.tokens), want[i])


# -- unified telemetry: lifecycle tracing through the engine ---------------


def test_engine_trace_complete_span_chain_per_request(rng):
    """Acceptance: a mixed burst (bucketed + chunked + speculative) under
    a Tracer yields ONE complete span chain per request — queue ->
    prefill[/chunk] -> decode/verify -> finish — on one track per slot
    plus the scheduler track, and the Chrome export round-trips."""
    import json

    from tpu_parallel.obs import Tracer, write_chrome_trace

    cfg, model, _, params = _build(rng)
    tracer = Tracer()
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        tracer=tracer, prefill_chunk_tokens=4, draft_tokens=3,
    )
    prompts = [
        [1, 2, 3, 4, 5, 6, 7, 8, 9, 10],  # > chunk budget: chunked path
        [3, 4, 5],  # bucketed path
        [5, 6, 7, 8],  # joins after a slot frees
    ]
    outs = [eng.add_request(_req(p, 5)) for p in prompts]
    eng.run()
    assert all(out.status == FINISHED for out in outs)

    assert tracer.tracks() == ["scheduler", "slot 0", "slot 1"]
    for out in outs:
        rid = out.request.request_id
        chain = [
            s.name for s in tracer.spans if s.attrs.get("request_id") == rid
        ]
        assert chain[0] == "queue", chain
        assert any(name.startswith("prefill") for name in chain), chain
        assert any(name in ("decode", "verify") for name in chain), chain
        finishes = [
            ev for ev in tracer.instants
            if ev["attrs"].get("request_id") == rid
        ]
        assert len(finishes) == 1 and finishes[0]["name"] == "finish"
        # span chain is time-ordered within the request
        starts = [
            s.start for s in tracer.spans
            if s.attrs.get("request_id") == rid
        ]
        assert starts == sorted(starts)
    # chunked request: one prefill_chunk span per chunk, indexed
    chunked = [
        s for s in tracer.spans
        if s.name == "prefill_chunk"
        and s.attrs["request_id"] == outs[0].request.request_id
    ]
    assert [s.attrs["chunk"] for s in chunked] == list(range(len(chunked)))
    assert len(chunked) == 3  # 10 tokens / chunk 4 -> 3 chunks
    assert chunked[-1].attrs["final"] is True
    # verify spans carry draft K + acceptance attrs
    verifies = [s for s in tracer.spans if s.name == "verify"]
    assert verifies and all(
        "draft_k" in s.attrs and "accepted" in s.attrs for s in verifies
    )
    # export round-trips (field-level contract pinned in test_obs.py)
    path = write_chrome_trace(tracer, "/tmp/test_engine_trace.json")
    events = json.load(open(path))["traceEvents"]
    assert {e["ph"] for e in events} >= {"M", "X", "i", "b", "e"}


def test_engine_prefix_hit_trace_attrs_and_queue_span(rng):
    """Prefix-cache hits mark their prefill spans cache_hit=True, and a
    request that waits in the queue records a queue span covering the
    wait (fake clock: deterministic widths)."""
    from tpu_parallel.obs import Tracer

    cfg, model, _, params = _build(rng)
    clock = [0.0]

    def fake_clock():
        clock[0] += 0.25
        return clock[0]

    tracer = Tracer(clock=fake_clock)
    # per-step tick: the stall-cause assertions below need pure decode
    # ticks ("none") to exist, which a fused tick folds away
    eng = ServingEngine(
        model, params, n_slots=1, clock=fake_clock,
        prefill_buckets=(8, 16), prefix_cache_size=2, tracer=tracer,
        decode_steps_per_tick=1,
    )
    shared = [7, 3, 5, 2, 9, 4, 6, 1]  # one full bucket: a storable prefix
    outs = [
        eng.add_request(_req(shared + [5, 6], 4)),
        eng.add_request(_req(shared + [8, 2], 4)),
    ]
    eng.run()
    assert all(out.status == FINISHED for out in outs)
    assert eng.metrics.prefix_hits >= 1
    prefills = {
        s.attrs["request_id"]: s for s in tracer.spans if s.name == "prefill"
    }
    assert prefills[outs[0].request.request_id].attrs["cache_hit"] is False
    hit_span = prefills[outs[1].request.request_id]
    assert hit_span.attrs["cache_hit"] is True
    assert hit_span.attrs["prefix_len"] == len(shared)
    # the second request queued behind a 1-slot pool: its queue span is
    # wider than the first's and closed before its prefill began
    queues = {
        s.attrs["request_id"]: s for s in tracer.spans if s.name == "queue"
    }
    q0 = queues[outs[0].request.request_id]
    q1 = queues[outs[1].request.request_id]
    assert q1.end - q1.start > q0.end - q0.start
    assert q1.end <= hit_span.start
    # stall-cause counters cover the run: prefill ticks + decode ticks
    stalls = {
        row["labels"]["cause"]: row["value"]
        for row in eng.registry.snapshot()["counters"]
        if row["name"] == "serving_tick_stall_total"
    }
    assert stalls["prefill"] >= 2 and stalls["none"] >= 1
    # scheduler published queue telemetry into the engine registry
    waits = [
        row for row in eng.registry.snapshot()["histograms"]
        if row["name"] == "serving_queue_wait_seconds"
    ]
    assert waits and waits[0]["count"] == 2


def test_engine_reset_metrics_rewires_scheduler_registry(rng):
    cfg, model, prompt, params = _build(rng, n_rows=1)
    eng = ServingEngine(model, params, n_slots=1)
    assert eng.scheduler.registry is eng.registry
    old_registry = eng.registry
    eng.add_request(_req(prompt[0], 3))
    eng.run()
    fresh = eng.reset_metrics()
    assert fresh is eng.metrics
    assert eng.registry is fresh.registry is not old_registry
    assert eng.scheduler.registry is eng.registry
    assert eng.metrics.ticks == 0
    # the engine still serves correctly after the swap
    out = eng.add_request(_req(prompt[0], 3))
    eng.run()
    assert out.status == FINISHED and eng.metrics.finished == 1


# -- device-side NaN/Inf integrity sentinel ----------------------------------


def _poison(params):
    """Every floating leaf becomes NaN — the corrupted-weights shape
    that would otherwise stream confident garbage."""
    return jax.tree_util.tree_map(
        lambda x: (
            jnp.full_like(x, jnp.nan)
            if jnp.issubdtype(x.dtype, jnp.floating) else x
        ),
        params,
    )


def test_nan_sentinel_fails_request_typed_at_prefill(rng):
    """Non-finite logits at the FIRST sampled token: the request FAILS
    typed ``integrity`` with zero tokens streamed, the slot releases,
    and the trip is counted — never a garbage stream."""
    from tpu_parallel.serving import FAIL_INTEGRITY, FAILED

    cfg, model, prompt, params = _build(rng)
    eng = ServingEngine(
        model, _poison(params), n_slots=2, decode_steps_per_tick=1
    )
    events = []
    out = eng.add_request(_req(prompt[0], 6, on_token=events.append))
    eng.run(max_ticks=20)
    assert out.status == FAILED
    assert out.finish_reason == FAIL_INTEGRITY
    assert out.tokens == []
    assert eng.integrity_trips == 1
    assert eng.metrics.summary()["integrity_trips"] == 1
    assert eng.pool.n_free == eng.pool.n_slots  # slot released
    assert len(events) == 1 and events[0].finished
    assert events[0].finish_reason == FAIL_INTEGRITY
    assert events[0].token == -1  # the sentinel never streams
    assert not eng.has_work()


def test_nan_sentinel_mid_stream_fused_tick(rng):
    """Weights rot AFTER tokens already streamed, under the fused
    multi-step tick: delivery stops at the trip (already-delivered
    tokens stand), the request fails typed, and the pool stays clean."""
    from tpu_parallel.serving import FAIL_INTEGRITY, FAILED

    cfg, model, prompt, params = _build(rng)
    eng = ServingEngine(
        model, params, n_slots=2, decode_steps_per_tick=4
    )
    out = eng.add_request(_req(prompt[0], 12))
    eng.step()
    assert out.status == "running" and len(out.tokens) >= 1
    delivered = list(out.tokens)
    eng.params = _poison(params)  # the rot lands mid-flight
    eng.run(max_ticks=10)
    assert out.status == FAILED
    assert out.finish_reason == FAIL_INTEGRITY
    assert out.tokens == delivered  # nothing after the trip streamed
    assert eng.integrity_trips == 1
    assert eng.pool.n_free == eng.pool.n_slots
    assert not eng.has_work()


def test_nan_sentinel_escalates_replica_to_degraded(rng):
    """The cluster view: a sentinel trip flips the replica HEALTHY ->
    DEGRADED (routers deprioritize it) without killing it — an
    escalation, not a death."""
    from tpu_parallel.cluster.replica import DEGRADED, ReplicaHandle

    cfg, model, prompt, params = _build(rng)
    eng = ServingEngine(
        model, _poison(params), n_slots=2, decode_steps_per_tick=1
    )
    handle = ReplicaHandle(0, eng)
    handle.submit(_req(prompt[0], 4))
    for _ in range(10):
        handle.step()
        if handle.health == DEGRADED:
            break
    assert handle.health == DEGRADED
    assert eng.integrity_trips == 1
    assert handle.open_requests == 0  # the failed request left the ledger


@pytest.mark.parametrize("spec_steps", [1, 2])
def test_nan_sentinel_spec_verify_path(rng, spec_steps):
    """The sentinel covers speculative decoding too — per-step verify
    AND the fused verify scan: weights rotting mid-stream under
    draft-verify ticks fail the request typed instead of delivering an
    argmax-over-NaN token chain."""
    from tpu_parallel.serving import FAIL_INTEGRITY, FAILED

    cfg, model, prompt, params = _build(rng)
    eng = ServingEngine(
        model, params, n_slots=2, draft_tokens=3,
        decode_steps_per_tick=spec_steps,
    )
    out = eng.add_request(_req(prompt[0], 12))
    eng.step()
    assert out.status == "running" and len(out.tokens) >= 1
    delivered = list(out.tokens)
    eng.params = _poison(params)
    eng.run(max_ticks=10)
    assert out.status == FAILED
    assert out.finish_reason == FAIL_INTEGRITY
    assert out.tokens == delivered
    assert eng.integrity_trips == 1
    assert eng.pool.n_free == eng.pool.n_slots
    assert not eng.has_work()
