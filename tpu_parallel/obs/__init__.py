"""Unified telemetry: labeled metric registry, request-lifecycle span
tracer, and pluggable exporters (Chrome trace / Prometheus text / JSONL).

Shared by the serving engine and the trainer (docs/11_observability.md):
``MetricRegistry`` is the one store every counter/gauge/histogram lives
in, ``Tracer`` records lifecycle spans on per-slot tracks, and the
exporters serialize both without touching instrumentation.
"""

from tpu_parallel.obs.exporters import (
    chrome_trace_events,
    export_snapshot_jsonl,
    prometheus_lines,
    prometheus_text,
    write_chrome_trace,
    write_prometheus,
)
from tpu_parallel.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    HistogramWindow,
    MetricRegistry,
    PercentileWindow,
    validate_snapshot,
)
from tpu_parallel.obs.tracer import (
    NULL_SPAN,
    NULL_TRACER,
    NullTracer,
    Span,
    Tracer,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "HistogramWindow",
    "PercentileWindow",
    "MetricRegistry",
    "validate_snapshot",
    "Span",
    "Tracer",
    "NullTracer",
    "NULL_SPAN",
    "NULL_TRACER",
    "chrome_trace_events",
    "write_chrome_trace",
    "prometheus_lines",
    "prometheus_text",
    "write_prometheus",
    "export_snapshot_jsonl",
]
