"""Write-ahead request journal: the daemon's crash-recovery contract.

Append-only JSONL with monotone sequence numbers, batched fsync, and a
per-record CRC32.  Every record the daemon must not lose across a
``kill -9`` goes through here BEFORE the effect is acknowledged to a
client:

- ``submit``   — an ACCEPTED submission (the full request payload plus
  the client's dedupe token).  Synced durably before the accept is
  returned, so an acknowledged request can never vanish.  The payload
  field names are intentionally the serve_bench trace-schema names
  (``arrival`` / ``prompt`` / ``prompt_len`` / ``prefix_group`` /
  ``priority`` / ``deadline`` / ``max_new_tokens``) — ONE workload
  exchange format, so ``serve_bench --trace-replay`` (alias
  ``--workload``) replays a production journal directly.
- ``tokens``   — tokens delivered to a request this tick (``index`` is
  the position of the first one).  Batched per tick; a torn tail loses
  at most the unsynced suffix, and greedy recovery regenerates exactly
  those tokens (forced-prefix replay is bitwise).
- ``terminal`` — a request reached a terminal state (status + typed
  ``finish_reason``).  A journaled terminal is what makes the dedupe
  token idempotent: a resubmission after it returns the completed
  record instead of re-admitting.
- ``decision`` — swap rollouts, autopilot actions, drain begin, the
  degraded-mode trip: the operator-action audit trail.
- ``recovery`` — a restart replayed the journal (counts ride along).
- ``shutdown`` — the process exited; ``clean`` distinguishes a drained
  exit (nothing open) from a forced fast shutdown (the journal IS the
  recovery contract for whatever was still open).

Integrity model: every record is written with a trailing ``crc``
field — CRC32 over its own serialization without that field — and
verified WHEN PRESENT on read (a pre-CRC journal still replays
unchanged).  A CRC-failed or unparseable record at the very END of the
file is the torn-write/bit-rot tail shape: tolerated by
:func:`read_journal` (``torn`` counts it) and TRUNCATED by
:func:`drop_torn_tail` before any reopen-for-append.  The same damage
anywhere else raises a typed :class:`JournalCorrupt` — ``reason`` is
``"garbage"`` (unparseable), ``"crc"`` (parseable but checksum-failed)
or ``"seq_regression"`` (order lies) — because a journal that cannot
prove its own contents must not drive recovery.

Durability model: every ``append`` writes and flushes the line to the
OS immediately (a crashed *process* loses nothing flushed); ``fsync``
— the expensive disk barrier that survives a crashed *machine* — is
batched: forced for ``submit``/``shutdown`` records, otherwise issued
once at least ``fsync_batch`` records are pending (``sync()`` at each
tick boundary).  All file operations route through the injectable
fault shim (:mod:`tpu_parallel.daemon.iofaults`), so seeded media
failure — ``EIO`` on fsync, ``ENOSPC`` mid-append, read-side bit
flips — soaks the whole stack deterministically
(``scripts/daemon_bench.py --disk-faults``).

Growth model: :meth:`JournalWriter.rotate` compacts the journal into a
fresh segment — a meta record plus a caller-provided snapshot of the
OPEN state (submit + tokens + terminal records per live request, with
fresh monotone seqs) — written to a sidecar, fsynced, and atomically
``os.replace``d over the old file.  Restart replay is therefore
O(open requests + retained completions), not O(lifetime); a crash at
ANY point leaves exactly one authoritative file (the sidecar is
ignored and removed until the atomic replace lands).

Timestamps come from the injected clock and are only comparable within
one process lifetime (the wall clock is monotonic per process) — replay
logic never compares times across a restart, only sequence numbers.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from tpu_parallel.daemon import iofaults

JOURNAL_VERSION = 2  # 1 = PR 14 (no CRC); 2 adds per-record crc + rotation

# record kinds (the "record" field)
REC_META = "journal_meta"
REC_SUBMIT = "submit"
REC_TOKENS = "tokens"
REC_TERMINAL = "terminal"
REC_DECISION = "decision"
REC_RECOVERY = "recovery"
REC_SHUTDOWN = "shutdown"

# record kinds whose append forces an immediate fsync: an accepted
# submission must be durable before the client hears "accepted", a
# recovery record is the restart's first promise, and a shutdown record
# is the last thing the process does
_SYNC_NOW = frozenset({REC_SUBMIT, REC_RECOVERY, REC_SHUTDOWN})

# the compaction sidecar: authoritative ONLY after the atomic replace
ROTATE_SUFFIX = ".compact"

# tail-damage tolerance, in LINES: one interrupted/rotted record — but a
# single flipped bit can turn a payload byte into "\n" and split that
# record into TWO unparseable lines, so the tolerated trailing run is 2.
# Anything longer (or any bad line with a good record after it) is
# corruption a torn write cannot explain.
MAX_TORN_TAIL_LINES = 2

# typed JournalCorrupt reasons (the corruption matrix's vocabulary)
CORRUPT_GARBAGE = "garbage"  # unparseable mid-file bytes
CORRUPT_CRC = "crc"  # parseable record whose checksum disagrees
CORRUPT_SEQ = "seq_regression"  # sequence numbers went backwards


class JournalCorrupt(RuntimeError):
    """The journal failed its integrity scan somewhere a torn tail
    cannot explain.  ``reason`` is one of ``CORRUPT_GARBAGE`` /
    ``CORRUPT_CRC`` / ``CORRUPT_SEQ`` — each damage class is typed
    distinctly so operators (and tests) can tell bit rot from a logic
    bug."""

    def __init__(self, message: str, reason: str = CORRUPT_GARBAGE):
        super().__init__(message)
        self.reason = reason


def encode_record(rec: Dict) -> Tuple[str, int]:
    """Serialize ``rec`` (which must not already carry ``crc``) as one
    journal line with a trailing ``crc`` field: CRC32 over the
    serialization WITHOUT it.  Writing the checksum as the textual last
    key is what makes verification exact: a parsed dict preserves file
    key order, so re-serializing it minus ``crc`` reproduces these
    bytes."""
    body = json.dumps(rec)
    crc = zlib.crc32(body.encode("utf-8"))
    return body[:-1] + f', "crc": {crc}}}', crc


def record_crc_ok(rec: Dict) -> Optional[bool]:
    """Verify one parsed record against its ``crc`` field.

    Returns None for a record WITHOUT a checksum (a pre-CRC journal —
    verified when present, so PR 14 journals replay unchanged), True
    when the recomputed CRC32 matches, False on any mismatch.  This is
    THE shared verification helper: :func:`read_journal` and
    ``scripts/serve_bench.py``'s ``load_trace`` both call it, so
    recovery and workload replay reject a corrupted record
    identically."""
    stored = rec.get("crc")
    if stored is None:
        return None
    rest = {k: v for k, v in rec.items() if k != "crc"}
    return zlib.crc32(json.dumps(rest).encode("utf-8")) == stored


class JournalWriter:
    """Append-only JSONL writer with sequence numbers, per-record CRC
    and batched fsync.

    ``clock`` is injectable (the daemon passes its :class:`~tpu_parallel.
    daemon.wallclock.WallClock`); every record gets ``seq`` (monotone,
    continuing across restarts via ``next_seq``), ``at`` (clock time,
    process-local) and ``crc``.  ``fsync_batch`` records may ride the OS
    page cache between disk barriers — except the kinds in ``_SYNC_NOW``,
    which sync before ``append`` returns.  All file ops go through
    :mod:`~tpu_parallel.daemon.iofaults`, so append/fsync failures are
    injectable; a failed append may leave a torn prefix in the file —
    :meth:`repair` truncates it so the writer can continue without
    welding the next record into mid-file garbage.
    """

    def __init__(
        self,
        path: str,
        clock: Callable[[], float],
        *,
        fsync_batch: int = 32,
        next_seq: int = 0,
    ):
        if fsync_batch < 1:
            raise ValueError(f"fsync_batch={fsync_batch} < 1")
        self.path = path
        self.clock = clock
        self.fsync_batch = fsync_batch
        self._seq = next_seq
        self._pending = 0  # records flushed to OS but not yet fsynced
        self.records = 0  # lifetime appends (this writer)
        self.records_since_rotate = 0  # the compaction trigger's counter
        self.fsyncs = 0
        self.rotations = 0
        # the disk refused even the post-failure repair: appends are
        # permanently unsafe (welding risk) — the daemon degrades
        self.wedged = False
        # a crash between writing the compaction sidecar and the atomic
        # replace leaves an orphan: the old file is still authoritative,
        # the sidecar never became the journal — drop it
        if os.path.exists(path + ROTATE_SUFFIX):
            os.remove(path + ROTATE_SUFFIX)
        self.truncated_tail = drop_torn_tail(path)
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self._fh = iofaults.open_file(path, "a", encoding="utf-8")
        if fresh:
            self.append({"record": REC_META, "journal_version": JOURNAL_VERSION})
            self.sync()

    def append(self, record: Dict) -> Dict:
        """Assign seq + timestamp + crc, write one line, flush to the
        OS.  Returns the full record as written.  Sync-now kinds fsync
        before returning; everything else waits for :meth:`sync`.

        Failure contract: ``append`` raises ``OSError`` ONLY with the
        record absent from the journal — a torn write is repaired
        (truncated) in place, and a sync-now record whose fsync barrier
        failed is WITHDRAWN (the durability promise was never made, so
        a later crash must not resurrect an un-acknowledged accept).
        If the disk refuses even that cleanup, ``wedged`` flips and
        every further append refuses fast — the caller degrades."""
        if self.wedged or self._fh.closed:
            # a closed handle (failed repair/rotate reopen) must surface
            # as the OSError the degraded-mode accounting understands,
            # never as a ValueError that escapes every handler
            raise OSError("journal wedged: no usable file handle")
        rec = dict(record)
        rec["seq"] = self._seq
        self._seq += 1
        rec.setdefault("at", round(self.clock(), 6))
        line, crc = encode_record(rec)
        rec["crc"] = crc
        data = line + "\n"
        try:
            iofaults.write_line(self._fh, data)
            self._fh.flush()
        except OSError:
            # a torn prefix may be in the file: truncate it NOW, or the
            # next append welds into mid-file garbage
            if not self.repair():
                self.wedged = True
            raise
        self.records += 1
        self.records_since_rotate += 1
        self._pending += 1
        if rec.get("record") in _SYNC_NOW:
            try:
                self.sync()
            except OSError:
                # the record is in the file but its durability barrier
                # failed — withdraw it so the accept the caller is
                # about to refuse cannot come back from the dead on
                # the next recovery
                if self._withdraw_tail(len(data.encode("utf-8"))):
                    self.records -= 1
                    self.records_since_rotate -= 1
                    self._pending -= 1
                else:
                    self.wedged = True
                raise
        elif self._pending >= self.fsync_batch:
            try:
                self.sync()
            except OSError:
                # opportunistic batch barrier only: the record itself
                # is safely appended, the tick-boundary sync() retries
                # the fsync and its owner counts the failure — raising
                # here would make the caller believe the append failed
                pass
        return rec

    def _withdraw_tail(self, nbytes: int) -> bool:
        """Truncate the last ``nbytes`` of the journal — the record just
        appended (single-writer: nothing can have landed after it) whose
        sync-now barrier failed.  Returns False when the disk refuses."""
        try:
            self._fh.close()
            with iofaults.open_file(self.path, "rb+") as fh:
                fh.seek(0, os.SEEK_END)
                fh.truncate(max(0, fh.tell() - nbytes))
            self._fh = iofaults.open_file(self.path, "a", encoding="utf-8")
            return True
        except OSError:
            return False

    def sync(self) -> bool:
        """Batched disk barrier: fsync when anything is pending (tick
        boundary) — a no-op on a clean writer.  Returns whether a real
        fsync was issued.  An injected/real ``EIO`` propagates with
        ``_pending`` intact, so the next tick retries the barrier."""
        if self._pending == 0:
            return False
        if self.wedged or self._fh.closed:
            raise OSError("journal wedged: no usable file handle")
        self._fh.flush()
        iofaults.fsync_file(self._fh)
        self.fsyncs += 1
        self._pending = 0
        return True

    def repair(self) -> bool:
        """Recover the writer after a failed append: close the handle,
        truncate any torn tail fragment (the partial record the failed
        write left behind), and reopen for append.  Without this, the
        NEXT append would weld onto the fragment and brick the journal
        (mid-file garbage) on the following restart.  Returns False
        when the disk refuses even the repair — the caller degrades."""
        try:
            if not self._fh.closed:
                self._fh.close()
            drop_torn_tail(self.path)
            self._fh = iofaults.open_file(self.path, "a", encoding="utf-8")
            return True
        except OSError:
            return False

    def rotate(self, snapshot: List[Dict]) -> int:
        """Segment rotation + compaction: write a fresh segment holding
        a meta record plus ``snapshot`` (payload dicts WITHOUT seq/at/
        crc — they are re-stamped with fresh monotone seqs), fsync it,
        and atomically replace the journal with it.  The retired
        segment's records are gone: restart replay now reads O(snapshot)
        records instead of O(lifetime).  Crash-safe at every point — the
        sidecar is not the journal until ``os.replace`` lands, and a
        leftover sidecar is discarded at the next writer construction.
        Returns the new segment's record count."""
        self.sync()  # the retiring segment's tail must be durable first
        tmp = self.path + ROTATE_SUFFIX
        try:
            with iofaults.open_file(tmp, "w", encoding="utf-8") as fh:
                recs = [{
                    "record": REC_META,
                    "journal_version": JOURNAL_VERSION,
                    "compacted": True,
                }] + [dict(r) for r in snapshot]
                for rec in recs:
                    rec["seq"] = self._seq
                    self._seq += 1
                    rec.setdefault("at", round(self.clock(), 6))
                    line, _ = encode_record(rec)
                    iofaults.write_line(fh, line + "\n")
                fh.flush()
                iofaults.fsync_file(fh)
        except OSError:
            # a half-written sidecar is garbage, never the journal
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        self._fh.close()
        os.replace(tmp, self.path)
        try:
            self._fh = iofaults.open_file(self.path, "a", encoding="utf-8")
        except OSError:
            # the new segment IS the journal (replace landed) but we
            # cannot append to it: wedge so every later call refuses
            # with a typed OSError instead of a closed-handle ValueError
            self.wedged = True
            raise
        self._pending = 0
        self.records += len(recs)
        self.records_since_rotate = 0
        self.rotations += 1
        return len(recs)

    @property
    def next_seq(self) -> int:
        return self._seq

    def close(self) -> None:
        if not self._fh.closed:
            self.sync()
            self._fh.close()

    def abort(self) -> None:
        """Crash simulation for tests: drop the handle without the
        closing sync (flushed lines survive, like a SIGKILL'd process)."""
        if not self._fh.closed:
            self._fh.close()


def _line_start(fh, end: int) -> int:
    """Byte offset where the line containing/ending at ``end`` starts
    (chunked backward scan, so one long record never loads the file)."""
    pos = end
    while pos > 0:
        step = min(4096, pos)
        fh.seek(pos - step)
        chunk = fh.read(step)
        nl = chunk.rfind(b"\n")
        if nl != -1:
            return pos - step + nl + 1
        pos -= step
    return 0


def _tail_record_bad(line: bytes) -> bool:
    """Is this complete final line an unusable record?  Unparseable
    bytes, a non-record object, or a CRC mismatch all count — exactly
    the damage classes :func:`read_journal` tolerates at the tail."""
    try:
        rec = json.loads(line.decode("utf-8", errors="replace"))
    except ValueError:
        return True
    if not isinstance(rec, dict) or "record" not in rec:
        return True
    return record_crc_ok(rec) is False


def drop_torn_tail(path: str) -> int:
    """Truncate tail damage before APPENDING to a journal.

    ``read_journal`` tolerates a bad tail record while *reading*, but a
    writer reopening in append mode would concatenate its first record
    onto the damage — turning tolerable tail damage into mid-file
    garbage that bricks the journal (:class:`JournalCorrupt`) on the
    NEXT restart.  Two damage shapes truncate: an unterminated FRAGMENT
    (the write a crash interrupted — never durable, already ignored by
    the reader) and a complete final line that fails parse or CRC (the
    bit-rot shape — its payload is unusable, and recovery regenerates
    anything it held bitwise via forced-prefix replay).  Returns the
    bytes truncated (0 when the file is absent, empty, or clean)."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return 0
    dropped = 0
    with iofaults.open_file(path, "rb+") as fh:
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        fh.seek(size - 1)
        if fh.read(1) != b"\n":
            # unterminated fragment: scan back to the last complete
            # line's newline and cut
            keep = _line_start(fh, size)
            fh.truncate(keep)
            dropped += size - keep
            size = keep
        # the last COMPLETE record(s): parse + CRC check (a flipped bit
        # leaves the line intact but the checksum disagreeing — or
        # mints a "\n" that split one record into two bad lines, so the
        # sweep runs up to the reader's tail tolerance)
        for _ in range(MAX_TORN_TAIL_LINES):
            if size == 0:
                break
            start = _line_start(fh, size - 1)
            fh.seek(start)
            line = fh.read(size - start).rstrip(b"\n")
            if not _tail_record_bad(line):
                break
            fh.truncate(start)
            dropped += size - start
            size = start
        if dropped:
            fh.flush()
            iofaults.fsync_file(fh)
    return dropped


def read_journal(path: str) -> Tuple[List[Dict], int]:
    """Scan a journal file.  Returns ``(records, torn)`` where ``torn``
    counts dropped trailing damaged LINES (at most
    ``MAX_TORN_TAIL_LINES`` — the record a crash tore mid-write or a
    bit flip corrupted, which a flip minting a newline can split in
    two).  Damage anywhere else raises a
    typed :class:`JournalCorrupt` — ``reason`` distinguishes
    unparseable garbage, a CRC mismatch, and a sequence-number
    regression: a journal that lies about its contents or order must
    not drive recovery.  CRC fields are verified when present, so a
    pre-CRC (PR 14) journal replays unchanged.  The read goes through
    the fault shim, so seeded bit flips exercise this exact path."""
    records: List[Dict] = []
    # trailing run of damaged lines: (lineno, reason).  A good record
    # arriving while this is non-empty means the damage was MID-file;
    # a run longer than MAX_TORN_TAIL_LINES exceeds what one torn/
    # rotted record can explain.  Split on "\n" ONLY — the bytes the
    # writer delimits with, and the same splitting serve_bench's
    # load_trace uses (a flipped bit must not read differently through
    # the two surfaces; splitlines() would also split on form feeds and
    # unicode breaks a flip can mint).
    bad_run: List[Tuple[int, str]] = []
    for lineno, line in enumerate(
        iofaults.read_text(path).split("\n"), 1
    ):
        line = line.strip()
        if not line:
            continue
        reason = None
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            reason = CORRUPT_GARBAGE
            rec = None
        if reason is None and (
            not isinstance(rec, dict) or "record" not in rec
        ):
            reason = CORRUPT_GARBAGE
        if reason is None and record_crc_ok(rec) is False:
            reason = CORRUPT_CRC
        if reason is not None:
            bad_run.append((lineno, reason))
            if len(bad_run) > MAX_TORN_TAIL_LINES:
                at, why = bad_run[0]
                raise JournalCorrupt(
                    f"{path}:{at}: {why} damage spans more than "
                    f"{MAX_TORN_TAIL_LINES} lines — corrupt beyond a "
                    "torn write",
                    reason=why,
                )
            continue
        if bad_run:
            at, why = bad_run[0]
            raise JournalCorrupt(
                f"{path}:{at}: {why} record is not at the tail — the "
                "journal is corrupt beyond a torn write",
                reason=why,
            )
        records.append(rec)
    last = -1
    for rec in records:
        seq = rec.get("seq")
        if seq is None:
            continue
        if seq <= last:
            raise JournalCorrupt(
                f"{path}: sequence regressed {last} -> {seq}",
                reason=CORRUPT_SEQ,
            )
        last = seq
    return records, len(bad_run)


@dataclasses.dataclass
class JournalEntry:
    """Replay state for one journaled request: the submit payload, the
    durable token prefix, and the terminal record (None = the crash
    caught it accepted-but-unfinished — recovery re-admits it)."""

    submit: Dict
    tokens: List[int] = dataclasses.field(default_factory=list)
    terminal: Optional[Dict] = None

    @property
    def request_id(self) -> str:
        return self.submit["request_id"]

    @property
    def dedupe_token(self) -> Optional[str]:
        return self.submit.get("dedupe_token")

    @property
    def unfinished(self) -> bool:
        return self.terminal is None


@dataclasses.dataclass
class RecoveryState:
    """Everything a restart needs from the journal: per-request entries
    in submit order, the dedupe index, the next sequence number, and the
    scan's damage/shutdown accounting."""

    entries: Dict[str, JournalEntry]
    order: List[str]
    dedupe: Dict[str, str]  # dedupe_token -> request_id
    next_seq: int
    torn_records: int
    clean_shutdown: bool
    recoveries: int  # prior recovery records (restart count)
    decisions: int

    @property
    def unfinished(self) -> List[JournalEntry]:
        return [
            self.entries[rid]
            for rid in self.order
            if self.entries[rid].unfinished
        ]

    @property
    def finished(self) -> List[JournalEntry]:
        return [
            self.entries[rid]
            for rid in self.order
            if not self.entries[rid].unfinished
        ]


def replay_state(records: List[Dict], torn: int = 0) -> RecoveryState:
    """Fold a journal scan into :class:`RecoveryState`.  Token records
    apply by INDEX (idempotent across overlapping replays: a re-delivery
    of positions already durable overwrites them with identical values
    under greedy decoding); a terminal closes its entry."""
    entries: Dict[str, JournalEntry] = {}
    order: List[str] = []
    dedupe: Dict[str, str] = {}
    next_seq = 0
    clean = False
    recoveries = 0
    decisions = 0
    for rec in records:
        seq = rec.get("seq")
        if seq is not None:
            next_seq = max(next_seq, seq + 1)
        kind = rec.get("record")
        if kind == REC_SUBMIT:
            rid = rec["request_id"]
            if rid not in entries:  # duplicate submits cannot re-open
                entries[rid] = JournalEntry(submit=rec)
                order.append(rid)
                tok = rec.get("dedupe_token")
                if tok:
                    dedupe[tok] = rid
        elif kind == REC_TOKENS:
            entry = entries.get(rec["request_id"])
            if entry is None:
                continue
            index = int(rec.get("index", len(entry.tokens)))
            toks = [int(t) for t in rec.get("tokens", ())]
            del entry.tokens[index:]
            entry.tokens.extend(toks)
        elif kind == REC_TERMINAL:
            entry = entries.get(rec["request_id"])
            if entry is not None:
                entry.terminal = rec
        elif kind == REC_SHUTDOWN:
            clean = bool(rec.get("clean"))
        elif kind == REC_RECOVERY:
            recoveries += 1
            clean = False
        elif kind == REC_DECISION:
            decisions += 1
        if kind in (REC_SUBMIT, REC_TOKENS, REC_TERMINAL):
            clean = False  # work after a shutdown record reopens the log
    return RecoveryState(
        entries=entries,
        order=order,
        dedupe=dedupe,
        next_seq=next_seq,
        torn_records=torn,
        clean_shutdown=clean,
        recoveries=recoveries,
        decisions=decisions,
    )


def load_state(path: str) -> RecoveryState:
    """One-call journal scan + fold (missing/empty file = empty state)."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return replay_state([], 0)
    records, torn = read_journal(path)
    return replay_state(records, torn)
