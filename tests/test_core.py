"""Tests for core: state, metrics, rng folding, gradient accumulation."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import (
    Batch,
    TrainState,
    accumulate_gradients,
    accumulate_metrics,
    compute,
    fold_rng_over_axis,
    get_num_params,
    metric,
    sync_metrics,
)


def _make_state(rng, in_dim=16, out_dim=4):
    model = nn.Dense(out_dim)
    params = model.init(rng, jnp.zeros((1, in_dim)))["params"]
    return TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=optax.adamw(1e-3),
        rng=rng,
    )


def _loss_fn(params, apply_fn, batch, rng):
    logits = apply_fn({"params": params}, batch.inputs)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, batch.labels)
    bs = batch.inputs.shape[0]
    return loss.sum(), {"loss": (loss.sum(), bs)}


def _make_batch(rng, bs=32, in_dim=16, n_cls=4):
    k1, k2 = jax.random.split(rng)
    return Batch(
        inputs=jax.random.normal(k1, (bs, in_dim)),
        labels=jax.random.randint(k2, (bs,), 0, n_cls),
    )


def test_train_state_carries_rng(rng):
    state = _make_state(rng)
    assert state.rng is not None
    assert get_num_params(state) == 16 * 4 + 4


def test_accumulate_scan_equals_loop(rng):
    """Scan-based and loop-based accumulation must be numerically identical."""
    state = _make_state(rng)
    batch = _make_batch(jax.random.PRNGKey(1))
    g_loop, m_loop = accumulate_gradients(state, batch, rng, 4, _loss_fn, use_scan=False)
    g_scan, m_scan = accumulate_gradients(state, batch, rng, 4, _loss_fn, use_scan=True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5), g_loop, g_scan
    )
    np.testing.assert_allclose(m_loop["loss"][0], m_scan["loss"][0], rtol=1e-5)
    assert m_scan["loss"][1] == 32  # counts summed over 4 minibatches of 8


def test_accumulate_matches_full_batch(rng):
    """Accumulated mean gradient == full-batch gradient (for a sum loss / N)."""
    state = _make_state(rng)
    batch = _make_batch(jax.random.PRNGKey(2))
    g_full, _ = accumulate_gradients(state, batch, rng, 1, _loss_fn)
    g_acc, _ = accumulate_gradients(state, batch, rng, 4, _loss_fn)
    # accumulation divides by num_minibatches; full batch is the raw sum
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a / 4, b, rtol=2e-4, atol=1e-6),
        g_full,
        g_acc,
    )


def test_fold_rng_decorrelates(mesh_data8):
    def body(rng):
        folded = fold_rng_over_axis(rng, "data")
        return jax.random.normal(folded, (1, 4))

    f = jax.jit(
        jax.shard_map(body, mesh=mesh_data8, in_specs=P(), out_specs=P("data"))
    )
    out = f(jax.random.PRNGKey(0))
    assert out.shape == (8, 4)
    # all 8 per-device draws distinct
    assert len({tuple(np.asarray(r).tolist()) for r in out}) == 8


def test_sync_metrics_psum(mesh_data8):
    def body(x):
        m = {"loss": metric(x.sum(), x.shape[0])}
        return sync_metrics(m, "data")

    f = jax.jit(
        jax.shard_map(body, mesh=mesh_data8, in_specs=P("data"), out_specs=P())
    )
    m = f(jnp.arange(16.0))
    vals = compute(m)
    assert vals["loss"] == pytest.approx(120.0 / 16.0)


def test_accumulate_metrics():
    a = {"loss": (jnp.float32(2.0), jnp.float32(4.0))}
    b = {"loss": (jnp.float32(1.0), jnp.float32(4.0))}
    c = accumulate_metrics(a, b)
    assert float(c["loss"][0]) == 3.0
    assert accumulate_metrics(None, a) is a
