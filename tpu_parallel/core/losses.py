"""Loss functions in the (params, apply_fn, batch, rng) -> (loss, metrics) shape.

Capability parity: the reference's two near-identical ``loss_fn``s
(``data_paral.py:171-189``, ``param_sharding.py:325-340``) — softmax CE with
``(sum, count)`` metrics and dropout RNG folded over the mesh so replicas
decorrelate.  Generalized with an LM variant for the transformer configs.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from tpu_parallel.core.metrics import Metrics
from tpu_parallel.core.rng import fold_rng_over_axis
from tpu_parallel.core.state import Batch, TextBatch

AxisNames = Union[str, Sequence[str]]


def token_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-token CE with fp32 math from logits of any dtype.

    Models emit bf16 logits (their matmuls already round to bf16 — a model-
    side fp32 cast would only double the [B, S, vocab] HBM footprint, the
    dominant buffer at GPT-2 vocab sizes).  The upcast here fuses into the
    log-softmax reductions on TPU, so no fp32 logits tensor materializes.
    """
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )


def make_classification_loss(fold_axes: AxisNames = "data") -> Callable:
    """Softmax-CE loss for ``Batch``; dropout rng folded over ``fold_axes``."""

    def loss_fn(params, apply_fn, batch: Batch, rng: jax.Array):
        dropout_rng = fold_rng_over_axis(rng, fold_axes)
        logits = apply_fn(
            {"params": params}, batch.inputs, train=True, rngs={"dropout": dropout_rng}
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, batch.labels)
        correct = (logits.argmax(-1) == batch.labels).sum()
        bs = batch.labels.size
        metrics: Metrics = {
            "loss": (loss.sum(), jnp.float32(bs)),
            "accuracy": (correct.astype(jnp.float32), jnp.float32(bs)),
        }
        return loss.mean(), metrics

    return loss_fn


def make_lm_loss(fold_axes: AxisNames = "data") -> Callable:
    """Next-token cross-entropy for ``TextBatch`` with loss masking."""

    def loss_fn(params, apply_fn, batch: TextBatch, rng: jax.Array):
        dropout_rng = fold_rng_over_axis(rng, fold_axes)
        logits = apply_fn(
            {"params": params},
            batch.tokens,
            positions=batch.positions,
            train=True,
            rngs={"dropout": dropout_rng},
        )
        loss = token_cross_entropy(logits, batch.targets)
        mask = (
            batch.loss_mask
            if batch.loss_mask is not None
            else jnp.ones_like(loss, jnp.float32)
        )
        loss = loss * mask
        n_tok = mask.sum()
        correct = ((logits.argmax(-1) == batch.targets) * mask).sum()
        metrics: Metrics = {
            "loss": (loss.sum(), n_tok),
            "accuracy": (correct.astype(jnp.float32), n_tok),
        }
        return loss.sum() / jnp.maximum(n_tok, 1.0), metrics

    return loss_fn
