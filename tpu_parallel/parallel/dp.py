"""Data parallelism over a mesh axis.

Capability parity: ``data_paral.py`` in the reference — batch sharded over a
``"data"`` axis, state replicated, gradients all-reduced with ``pmean``,
metrics with ``psum``, buffers donated.  Rebuilt as a reusable train-step
*builder* instead of a script: any model + loss, any mesh (the data axis can
coexist with model/pipe/seq axes), scan-based accumulation by default.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_parallel.core.accumulate import LossFn, accumulate_gradients
from tpu_parallel.core.metrics import Metrics, sync_metrics
from tpu_parallel.core.state import TrainState


def sync_gradients_dp(grads, axis_names: Union[str, Sequence[str]] = "data"):
    """All-reduce (mean) gradients over the data axis (``data_paral.py:210-212``)."""
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    with jax.named_scope("sync_grads"):
        return jax.tree_util.tree_map(lambda g: lax.pmean(g, axis_names), grads)


def make_train_step(
    loss_fn: LossFn,
    *,
    data_axis: str = "data",
    num_minibatches: int = 1,
    use_scan: bool = True,
    donate: bool = True,
    mesh: Optional[Mesh] = None,
) -> Callable:
    """Build a jitted DP train step: ``(state, metrics, batch) -> (state, metrics)``.

    The returned function is ``jit(shard_map(...))`` over ``mesh`` with the
    batch sharded on ``data_axis`` and state/metrics replicated — the
    shard_map-explicit SPMD idiom, which on TPU lowers the two collectives
    (grad pmean, metric psum) straight onto ICI.

    With ``mesh=None`` the *unwrapped SPMD body* is returned instead: it uses
    collectives over ``data_axis`` and is only callable inside a caller-owned
    ``shard_map``/``pjit`` region that binds that axis (this is how the
    composed DPxTPxPP trainer embeds it).  It will raise an unbound-axis
    error if called directly.
    """

    def step(state: TrainState, metrics: Optional[Metrics], batch):
        rng, step_rng = jax.random.split(state.rng)
        grads, step_metrics = accumulate_gradients(
            state, batch, step_rng, num_minibatches, loss_fn, use_scan=use_scan
        )
        grads = sync_gradients_dp(grads, data_axis)
        new_state = state.apply_gradients(grads=grads, rng=rng)
        step_metrics = sync_metrics(step_metrics, data_axis)
        if metrics is not None:
            step_metrics = jax.tree_util.tree_map(jnp.add, metrics, step_metrics)
        return new_state, step_metrics

    if mesh is None:
        return step

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(), P(data_axis)),
        out_specs=(P(), P()),
        check_vma=True,
    )
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())


def make_init(
    model_init: Callable[[jax.Array, Any], TrainState],
    *,
    data_axis: str = "data",
    mesh: Mesh,
) -> Callable:
    """Wrap a ``(rng, batch) -> TrainState`` initializer for a DP mesh.

    The batch is sharded over the data axis; the returned state is replicated
    (identical init on every device because the rng is not folded).
    """
    return jax.jit(
        jax.shard_map(
            model_init,
            mesh=mesh,
            in_specs=(P(), P(data_axis)),
            out_specs=P(),
            check_vma=True,
        )
    )
