"""Convert a local HuggingFace checkpoint into this framework's formats.

Reads a ``save_pretrained`` directory (GPT-2 or Llama family, auto-detected
from its config.json), converts the weights with
:mod:`tpu_parallel.models.hf`, and writes either

- ``--format orbax`` (default): a bare-params orbax checkpoint — restore
  with ``ocp.PyTreeCheckpointer().restore(out_dir)`` and pass to
  :func:`~tpu_parallel.models.generate.generate` (this is NOT a
  ``Checkpointer``/TrainState run directory), or
- ``--format int8``: the :func:`quantize_params` int8 export artifact,
  reloaded with :func:`tpu_parallel.models.quantize.load_int8_npz` +
  :func:`dequantize_params` (~4x smaller than fp32).

Usage:
    python scripts/convert_hf.py /path/to/hf_model /path/to/out \
        [--format orbax|int8] [--seq-len N]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_config(hf_dir: str, seq_len):
    with open(os.path.join(hf_dir, "config.json")) as fh:
        hc = json.load(fh)
    model_type = hc.get("model_type")
    from tpu_parallel.models import tiny_test

    if model_type == "gpt2":
        n_inner = hc.get("n_inner") or 4 * hc["n_embd"]
        if n_inner != 4 * hc["n_embd"]:
            raise SystemExit(
                f"n_inner={n_inner} != 4*n_embd={4 * hc['n_embd']} — "
                "TransformerConfig.mlp_ratio is an integer multiple of "
                "d_model, so this checkpoint's MLP width cannot be "
                "represented"
            )
        return (
            tiny_test(
                vocab_size=hc["vocab_size"],
                d_model=hc["n_embd"],
                n_layers=hc["n_layer"],
                n_heads=hc["n_head"],
                seq_len=seq_len or hc["n_positions"],
                positional="learned",
                norm="layernorm",
                mlp="gelu",
                norm_eps=hc.get("layer_norm_epsilon", 1e-5),
                scan_layers=False,  # converters emit the unrolled layout
                remat=False,
                dropout_rate=0.0,
            ),
            "gpt2",
        )
    if model_type == "llama":
        if hc.get("rope_scaling"):
            raise SystemExit(
                f"rope_scaling={hc['rope_scaling']} is not supported — the "
                "framework implements plain RoPE (rope_theta only); "
                "converting would produce silently wrong positions"
            )
        if hc["intermediate_size"] % hc["hidden_size"]:
            raise SystemExit(
                f"intermediate_size={hc['intermediate_size']} is not a "
                f"multiple of hidden_size={hc['hidden_size']} — "
                "TransformerConfig.mlp_ratio is an integer, so this "
                "checkpoint's MLP width cannot be represented"
            )
        return (
            tiny_test(
                vocab_size=hc["vocab_size"],
                d_model=hc["hidden_size"],
                n_layers=hc["num_hidden_layers"],
                n_heads=hc["num_attention_heads"],
                n_kv_heads=(
                    None
                    if hc.get("num_key_value_heads", hc["num_attention_heads"])
                    == hc["num_attention_heads"]
                    else hc["num_key_value_heads"]
                ),
                mlp_ratio=hc["intermediate_size"] // hc["hidden_size"],
                seq_len=seq_len or hc["max_position_embeddings"],
                positional="rope",
                norm="rmsnorm",
                mlp="swiglu",
                norm_eps=hc.get("rms_norm_eps", 1e-5),
                rope_theta=hc.get("rope_theta", 10000.0),
                scan_layers=False,  # converters emit the unrolled layout
                remat=False,
                dropout_rate=0.0,
            ),
            "llama",
        )
    raise SystemExit(f"unsupported model_type {model_type!r} (gpt2 | llama)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("hf_dir", help="local save_pretrained directory")
    ap.add_argument("out_dir", help="output directory")
    ap.add_argument("--format", choices=("orbax", "int8"), default="orbax")
    ap.add_argument("--seq-len", type=int, default=0, help="override seq_len")
    args = ap.parse_args()

    config, family = build_config(args.hf_dir, args.seq_len)

    import transformers

    from tpu_parallel.models.hf import from_hf_gpt2, from_hf_llama

    import jax

    if family == "gpt2":
        hf = transformers.GPT2LMHeadModel.from_pretrained(args.hf_dir)
        params = from_hf_gpt2(hf, config)
    else:
        hf = transformers.LlamaForCausalLM.from_pretrained(args.hf_dir)
        params = from_hf_llama(hf, config)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    print(f"{family}: {n_params / 1e6:.1f}M params converted")

    if args.format == "orbax":
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ck:
            ck.save(os.path.abspath(args.out_dir), params)
        print(
            f"orbax params written to {args.out_dir} — restore with "
            "ocp.PyTreeCheckpointer().restore(...)"
        )
    else:
        from tpu_parallel.models import quantize_params, quantized_nbytes
        from tpu_parallel.models.quantize import save_int8_npz

        q = quantize_params(params)
        os.makedirs(args.out_dir, exist_ok=True)
        out = os.path.join(args.out_dir, "params_int8.npz")
        save_int8_npz(out, q)
        print(
            f"int8 artifact written to {out} "
            f"({quantized_nbytes(q) / 1e6:.1f} MB vs "
            f"{quantized_nbytes(params) / 1e6:.1f} MB dense)"
        )


if __name__ == "__main__":
    main()
