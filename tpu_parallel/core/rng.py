"""PRNG discipline across mesh axes.

Capability parity: ``fold_rng_over_axis`` (reference ``data_paral.py:28-34``),
generalized to any number of mesh axes so DP x TP x PP composition gets a
well-defined key on every device.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
from jax import lax


def fold_rng_over_axis(rng: jax.Array, axis_names: Union[str, Sequence[str]]) -> jax.Array:
    """Derive a device-unique key by folding the mesh position into ``rng``.

    Use for anything that must differ per device (dropout on different data
    shards, per-stage init).  Leave the key unfolded for anything that must be
    identical across an axis (replicated init).
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    for name in axis_names:
        rng = jax.random.fold_in(rng, lax.axis_index(name))
    return rng


def split_rng_like(rng: jax.Array, tree) -> "jax.Array":
    """Split ``rng`` into a pytree of keys matching ``tree``'s structure."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    return jax.tree_util.tree_unflatten(treedef, list(keys))
