"""Data pipeline tests: memmap token datasets and global batch assembly."""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import compute
from tpu_parallel.data import DataLoader, TokenDataset, make_global_batch


@pytest.fixture
def token_file(tmp_path):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=10_000, dtype=np.uint16)
    path = tmp_path / "corpus.bin"
    TokenDataset.write_bin(str(path), tokens)
    return str(path), tokens


def test_dataset_windows_match_stream(token_file):
    path, tokens = token_file
    ds = TokenDataset(path, seq_len=64)
    assert ds.num_windows == (10_000 - 1) // 64
    w = ds.window(3)
    np.testing.assert_array_equal(w, tokens[3 * 64 : 3 * 64 + 65].astype(np.int32))


def test_dataset_batch_targets_are_shifted(token_file):
    path, _ = token_file
    ds = TokenDataset(path, seq_len=32)
    batch = ds.batch(np.array([0, 5, 7]))
    np.testing.assert_array_equal(batch.tokens[:, 1:], batch.targets[:, :-1])
    assert batch.tokens.shape == (3, 32)


def test_make_global_batch_is_sharded(token_file, mesh_data8):
    path, _ = token_file
    ds = TokenDataset(path, seq_len=16)
    local = ds.batch(np.arange(16))
    gb = make_global_batch(local, mesh_data8, P("data"))
    assert gb.tokens.shape == (16, 16)
    assert gb.tokens.sharding.spec == P("data")
    # content preserved through the lift
    np.testing.assert_array_equal(np.asarray(gb.tokens), local.tokens)


def test_loader_deterministic_and_disjoint(token_file, mesh_data8):
    path, _ = token_file
    ds = TokenDataset(path, seq_len=16)
    dl_a = DataLoader(ds, mesh_data8, global_batch_size=8, seed=1)
    dl_b = DataLoader(ds, mesh_data8, global_batch_size=8, seed=1)
    batches_a = [np.asarray(b.tokens) for b in dl_a.epoch(0)]
    batches_b = [np.asarray(b.tokens) for b in dl_b.epoch(0)]
    assert len(batches_a) == ds.num_windows // 8
    for a, b in zip(batches_a, batches_b):
        np.testing.assert_array_equal(a, b)
    # different epoch -> different order
    first_e1 = next(iter(dl_a.epoch(1)))
    assert not np.array_equal(batches_a[0], np.asarray(first_e1.tokens))


def test_loader_rejects_too_small_dataset(token_file, mesh_data8):
    path, _ = token_file
    ds = TokenDataset(path, seq_len=4096)  # only ~2 windows in 10k tokens
    with pytest.raises(ValueError, match="fewer than"):
        DataLoader(ds, mesh_data8, global_batch_size=8)


def test_batch_at_is_step_pure(token_file, mesh_data8):
    """batch_at(s) is a pure function of (seed, s) — the resume contract."""
    path, _ = token_file
    ds = TokenDataset(path, seq_len=16)
    dl = DataLoader(ds, mesh_data8, global_batch_size=8, seed=2)
    bpe = dl.batches_per_epoch
    # jump around epochs in arbitrary order; same step -> same batch
    probe = [0, bpe + 3, 1, 2 * bpe, bpe + 3, 0]
    seen = {}
    for s in probe:
        tok = np.asarray(dl.batch_at(s).tokens)
        if s in seen:
            np.testing.assert_array_equal(tok, seen[s])
        seen[s] = tok
    assert not np.array_equal(seen[0], seen[bpe + 3])


def test_loader_trains_gpt(token_file, mesh_data8, rng):
    """Real-data smoke test: loss decreases on memmap-fed batches."""
    import jax
    import optax

    from tpu_parallel.models import GPTLM, make_gpt_loss, tiny_test
    from tpu_parallel.parallel.spmd import build_train_functions

    path, _ = token_file
    cfg = tiny_test()
    ds = TokenDataset(path, seq_len=cfg.seq_len)
    dl = DataLoader(ds, mesh_data8, global_batch_size=8, seed=0)
    it = iter(dl)
    first_batch = next(it)

    model = GPTLM(cfg)
    tx = optax.adamw(3e-3)

    def model_init(r, b):
        from tpu_parallel.core.state import TrainState

        variables = model.init(
            {"params": r}, b.tokens, positions=b.positions, train=False
        )
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"], tx=tx, rng=r
        )

    funcs = build_train_functions(
        model_init, make_gpt_loss(cfg), mesh_data8, first_batch,
        batch_spec=P("data"), donate=False,
    )
    state = funcs.init_fn(rng, first_batch)
    state, m0 = funcs.step_fn(state, None, first_batch)
    first = compute(m0)["loss"]
    for _ in range(12):
        state, m = funcs.step_fn(state, None, next(it))
    assert compute(m)["loss"] < first


def test_holdout_split_disjoint_and_exhaustive(token_file, mesh_data8):
    """train/eval views: eval tokens are provably never sampled by train.

    Covers every epoch-0..2 train batch and every eval batch; window index
    sets must be disjoint, with eval = the stream's tail.
    """
    path, tokens = token_file
    ds = TokenDataset(path, seq_len=16)
    train = DataLoader(
        ds, mesh_data8, global_batch_size=8, seed=3, holdout_fraction=0.25
    )
    ev = train.eval_view()
    n_eval = int(round(ds.num_windows * 0.25))
    assert train.num_windows == ds.num_windows - n_eval
    assert ev.num_windows == n_eval

    def window_ids(loader, epochs):
        seen = set()
        for e in range(epochs):
            for b in range(loader.batches_per_epoch):
                batch = loader.batch_at(e * loader.batches_per_epoch + b)
                # recover window ids from the first token of each row
                for row in np.asarray(batch.tokens):
                    starts = np.flatnonzero(
                        tokens[: ds.num_windows * 16 : 16].astype(np.int32)
                        == row[0]
                    )
                    # match on the full row to disambiguate repeated tokens
                    wid = next(
                        int(s)
                        for s in starts
                        if np.array_equal(
                            tokens[s * 16 : s * 16 + 16].astype(np.int32), row
                        )
                    )
                    seen.add(wid)
        return seen

    train_ids = window_ids(train, 3)
    eval_ids = window_ids(ev, 1)
    assert train_ids and eval_ids
    assert train_ids.isdisjoint(eval_ids)
    assert max(train_ids) < ds.num_windows - n_eval
    assert min(eval_ids) >= ds.num_windows - n_eval


def test_eval_view_requires_holdout(token_file, mesh_data8):
    path, _ = token_file
    ds = TokenDataset(path, seq_len=16)
    with pytest.raises(ValueError, match="holdout_fraction"):
        DataLoader(ds, mesh_data8, global_batch_size=8).eval_view()


def test_prefetch_matches_sequential(token_file, mesh_data8):
    path, _ = token_file
    ds = TokenDataset(path, seq_len=16)
    dl = DataLoader(ds, mesh_data8, global_batch_size=8, seed=5)
    it = dl.prefetch(lookahead=3)
    for step in range(5):
        np.testing.assert_array_equal(
            np.asarray(next(it).tokens), np.asarray(dl.batch_at(step).tokens)
        )


# --- multi-file + packed datasets --------------------------------------------


@pytest.mark.fast
def test_token_dataset_multi_shard(tmp_path):
    """A sharded corpus yields every shard's windows, none crossing files."""
    from tpu_parallel.data import TokenDataset

    a = np.arange(0, 33, dtype=np.uint16)        # 2 windows of 16
    b = np.arange(100, 117, dtype=np.uint16)     # 1 window of 16
    pa, pb = str(tmp_path / "a.bin"), str(tmp_path / "b.bin")
    TokenDataset.write_bin(pa, a)
    TokenDataset.write_bin(pb, b)
    ds = TokenDataset([pa, pb], seq_len=16)
    assert ds.num_windows == 3
    np.testing.assert_array_equal(ds.window(0), a[:17])
    np.testing.assert_array_equal(ds.window(1), a[16:33])
    np.testing.assert_array_equal(ds.window(2), b[:17])


@pytest.mark.fast
def test_packed_dataset_rows():
    """Documents pack whole, segments/positions/masks line up, and the
    final token of each document is excluded from the loss."""
    from tpu_parallel.data import PackedDataset

    eos = 9
    # docs: [1 2 9], [3 4 5 9], [6 9], [7 8 9] with seq_len 8
    stream = np.asarray([1, 2, eos, 3, 4, 5, eos, 6, eos, 7, 8, eos], np.uint16)
    ds = PackedDataset(stream, seq_len=8, eos_id=eos)
    assert ds.num_windows == 2
    tokens, targets, seg, pos, mask = ds.row(0)
    np.testing.assert_array_equal(tokens, [1, 2, eos, 3, 4, 5, eos, eos])
    np.testing.assert_array_equal(seg, [1, 1, 1, 2, 2, 2, 2, 0])
    np.testing.assert_array_equal(pos, [0, 1, 2, 0, 1, 2, 3, 0])
    # last token of each doc (and padding) is masked out of the loss
    np.testing.assert_array_equal(mask, [1, 1, 0, 1, 1, 1, 0, 0])
    np.testing.assert_array_equal(targets[:2], [2, eos])
    np.testing.assert_array_equal(targets[3:6], [4, 5, eos])


@pytest.mark.fast
def test_packed_dataset_oversize_doc_split():
    from tpu_parallel.data import PackedDataset

    eos = 0
    stream = np.concatenate([np.arange(1, 20, dtype=np.uint16), [eos]])
    ds = PackedDataset(stream, seq_len=8, eos_id=eos)
    # 20-token doc -> chunks of 8, 8, 4: rows [8], [8], [4]
    assert ds.num_windows == 3
    t0, *_ = ds.row(0)
    np.testing.assert_array_equal(t0, np.arange(1, 9))


def test_packed_dataset_through_loader_and_model(mesh_data8):
    """PackedDataset drives DataLoader + a train step end-to-end; packed
    rows carry segment_ids so attention cannot cross documents."""
    from tpu_parallel.data import DataLoader, PackedDataset

    eos = 3
    rng = np.random.default_rng(0)
    docs = []
    for _ in range(200):
        n = int(rng.integers(3, 14))
        docs.append(np.append(rng.integers(4, 30, n), eos))
    stream = np.concatenate(docs).astype(np.uint16)
    ds = PackedDataset(stream, seq_len=32, eos_id=eos)
    dl = DataLoader(ds, mesh_data8, global_batch_size=16)
    batch = next(iter(dl))
    assert batch.segment_ids is not None
    assert int(jnp.max(batch.segment_ids)) >= 2

    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig

    config = TrainerConfig(
        model="tiny",
        model_overrides=dict(vocab_size=32, seq_len=32),
        mesh=MeshConfig(data=-1),
        global_batch_size=16,
        steps=3,
        log_every=10,
        donate=False,
    )
    trainer = Trainer(config)
    trainer.init()
    state, m = trainer.state, None
    for b in [dl.batch_at(s) for s in range(3)]:
        state, m = trainer.funcs.step_fn(state, m, b)
    from tpu_parallel.core import compute

    assert compute(m)["loss"] > 0
