"""Device-mesh construction for DP x FSDP x TP x PP (x SP) parallelism.

The reference only ever builds a 1-D mesh over one ``"data"`` axis inline in
each script (``data_paral.py:150-152``, ``param_sharding.py`` equivalent).
Here the mesh is a first-class object: named axes, arbitrary shape, built with
``jax.experimental.mesh_utils.create_device_mesh`` so the logical axes map onto
the physical ICI torus well (innermost axes get the tightest rings), and
DCN-aware when a pod spans multiple slices.

Axis convention (outermost -> innermost):

- ``pipe``  — pipeline stages.  Lowest-bandwidth traffic (one activation
  handoff per microbatch) so it tolerates the slowest links (DCN).
- ``data``  — data parallelism; FSDP shards parameters over this same axis
  (ZeRO-3 style), so its traffic is one gradient reduce-scatter + param
  all-gather per step.
- ``seq``   — sequence/context parallelism (ring attention KV rotation).
- ``model`` — tensor parallelism.  Per-layer activation collectives — the most
  latency-sensitive — so it sits innermost, on the fastest ICI ring.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"

# Outer-to-inner ordering used when materializing the physical mesh.
AXIS_ORDER: Tuple[str, ...] = (PIPE_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``-1`` on ``data`` means "all remaining devices"."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1

    def resolved(self, n_devices: int) -> "MeshConfig":
        fixed = self.model * self.pipe * self.seq
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by model*pipe*seq={fixed}"
                )
            data = n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh shape data={data} model={self.model} pipe={self.pipe} "
                f"seq={self.seq} does not cover {n_devices} devices"
            )
        return MeshConfig(data=data, model=self.model, pipe=self.pipe, seq=self.seq)

    def axis_sizes(self) -> dict:
        return {
            PIPE_AXIS: self.pipe,
            DATA_AXIS: self.data,
            SEQ_AXIS: self.seq,
            MODEL_AXIS: self.model,
        }


def make_mesh(
    config: MeshConfig = MeshConfig(),
    devices: Optional[Sequence] = None,
    *,
    allow_split_physical_axes: bool = True,
):
    """Build a ``jax.sharding.Mesh`` with named axes from a logical shape.

    Uses ``mesh_utils.create_device_mesh`` so that on TPU the logical axes are
    laid out along physical ICI rings ("model" innermost), and falls back to a
    plain reshape on CPU-simulated devices.  Drops axes of size 1 is NOT done —
    keeping all four axes means the same ``PartitionSpec``s work for every
    strategy combination (an axis of size 1 is free).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    cfg = config.resolved(len(devices))
    sizes = cfg.axis_sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)

    if devices[0].platform == "cpu":
        dev_array = np.asarray(devices).reshape(shape)
    else:
        from jax.experimental import mesh_utils

        try:
            dev_array = mesh_utils.create_device_mesh(
                shape,
                devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
        except (ValueError, AssertionError, NotImplementedError):
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def mesh_from_sizes(data: int = -1, model: int = 1, pipe: int = 1, seq: int = 1, devices=None):
    return make_mesh(MeshConfig(data=data, model=model, pipe=pipe, seq=seq), devices=devices)


def factor_mesh(n_devices: int, *, want_model: int = 1, want_pipe: int = 1) -> MeshConfig:
    """Best-effort factorization of ``n_devices`` into (pipe, data, model).

    Shrinks the requested model/pipe degrees to the largest divisors that fit.
    Useful for dry-runs where the device count is dictated from outside.
    """
    model = 1
    for m in range(min(want_model, n_devices), 0, -1):
        if n_devices % m == 0:
            model = m
            break
    rem = n_devices // model
    pipe = 1
    for p in range(min(want_pipe, rem), 0, -1):
        if rem % p == 0:
            pipe = p
            break
    return MeshConfig(data=rem // pipe, model=model, pipe=pipe, seq=1)
