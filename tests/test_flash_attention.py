"""Flash-attention kernel tests (interpret mode on CPU; same code as TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import make_packed_segments as _packed_segments
from tpu_parallel.ops.flash_attention import (
    flash_attention,
    reference_attention,
)


def _make_qkv(rng, b=2, s=256, h=2, d=64, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _ref_bshd(q, k, v):
    out = reference_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)
    )
    return out.transpose(0, 2, 1, 3)


def test_forward_matches_reference(rng):
    q, k, v = _make_qkv(rng)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = _ref_bshd(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_forward_rectangular_blocks(rng):
    q, k, v = _make_qkv(rng, s=256)
    out = flash_attention(q, k, v, block_q=128, block_k=64, interpret=True)
    ref = _ref_bshd(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_gradients_match_reference(rng):
    q, k, v = _make_qkv(rng, b=1, s=128, h=2, d=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=64, block_k=64, interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (_ref_bshd(q, k, v) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
            err_msg=f"d{name} mismatch",
        )


def test_causality(rng):
    """Future tokens must not influence earlier outputs."""
    q, k, v = _make_qkv(rng, b=1, s=128, h=1, d=32)
    out1 = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    # perturb the last 64 positions of k/v: first 64 outputs must be unchanged
    k2 = k.at[:, 64:].add(1.0)
    v2 = v.at[:, 64:].add(1.0)
    out2 = flash_attention(q, k2, v2, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out1[:, :64]), np.asarray(out2[:, :64]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(out1[:, 64:]), np.asarray(out2[:, 64:]))


def test_bf16_runs(rng):
    q, k, v = _make_qkv(rng, dtype=jnp.bfloat16, s=128)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = _ref_bshd(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=5e-2, atol=5e-2
    )


def test_fallback_on_odd_shapes(rng):
    """Indivisible seq falls back to the reference path, still correct."""
    q, k, v = _make_qkv(rng, s=100)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = _ref_bshd(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_attention_hook_in_model(rng):
    """flash_attention plugs into the model's attn_fn hook (bshd contract)."""
    from tpu_parallel.models.layers import causal_attention

    q, k, v = _make_qkv(rng, s=128)
    # model layers call attn_fn(q, k, v, segment_ids=...) in [B,S,H,D]
    out_hook = flash_attention(q, k, v, segment_ids=None, interpret=True)
    out_model = causal_attention(q, k, v, segment_ids=None)
    np.testing.assert_allclose(
        np.asarray(out_hook), np.asarray(out_model), rtol=2e-3, atol=2e-3
    )





def test_packed_forward_matches_reference(rng):
    """segment_ids run in-kernel (no fallback) and match the masked reference."""
    q, k, v = _make_qkv(rng, b=2, s=256)
    seg = _packed_segments(jax.random.PRNGKey(9), 2, 256)
    out = flash_attention(
        q, k, v, segment_ids=seg, block_q=64, block_k=64, interpret=True
    )
    ref = reference_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        segment_ids=seg,
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_packed_no_cross_segment_leakage(rng):
    """Perturbing segment 0's K/V must not change segment 1+ outputs."""
    q, k, v = _make_qkv(rng, b=1, s=128, h=1, d=32)
    seg = jnp.concatenate(
        [jnp.zeros((1, 64), jnp.int32), jnp.ones((1, 64), jnp.int32)], axis=1
    )
    out1 = flash_attention(q, k, v, segment_ids=seg, block_q=64, block_k=64, interpret=True)
    k2 = k.at[:, :64].add(1.0)
    v2 = v.at[:, :64].add(1.0)
    out2 = flash_attention(q, k2, v2, segment_ids=seg, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out1[:, 64:]), np.asarray(out2[:, 64:]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(out1[:, :64]), np.asarray(out2[:, :64]))


def test_packed_gradients_match_reference(rng):
    q, k, v = _make_qkv(rng, b=1, s=128, h=2, d=32)
    seg = _packed_segments(jax.random.PRNGKey(4), 1, 128)

    def loss_flash(q, k, v):
        return (
            flash_attention(
                q, k, v, segment_ids=seg, block_q=64, block_k=64, interpret=True
            )
            ** 2
        ).sum()

    def loss_ref(q, k, v):
        out = reference_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            segment_ids=seg,
        ).transpose(0, 2, 1, 3)
        return (out**2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
            err_msg=f"d{name} mismatch",
        )


def test_packed_model_trains_with_flash(rng):
    """End-to-end: a GPT with attn_impl='flash' accepts packed batches."""
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.core import compute
    from tpu_parallel.core.state import TextBatch, TrainState
    from tpu_parallel.data import lm_batch
    from tpu_parallel.models import GPTLM, make_gpt_loss, tiny_test
    from tpu_parallel.parallel.spmd import build_train_functions
    from tpu_parallel.runtime import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=8))
    cfg = tiny_test(attn_impl="flash", seq_len=64)
    base = lm_batch(jax.random.PRNGKey(0), 16, cfg.seq_len, cfg.vocab_size)
    seg = np.asarray(_packed_segments(jax.random.PRNGKey(2), 16, cfg.seq_len))
    batch = TextBatch(
        tokens=base.tokens, targets=base.targets, loss_mask=base.loss_mask,
        positions=base.positions, segment_ids=seg,
    )
    model = GPTLM(cfg)
    tx = optax.adamw(3e-3)

    def init(rng_, b):
        v = model.init({"params": rng_}, b.tokens, train=False)["params"]
        return TrainState.create(apply_fn=model.apply, params=v, tx=tx, rng=rng_)

    funcs = build_train_functions(
        init, make_gpt_loss(cfg), mesh, batch, batch_spec=P("data"), donate=False,
        # interpret-mode pallas inside the step: JAX vma limitation (see spmd)
        check_vma=False,
    )
    state = funcs.init_fn(rng, batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)["loss"]
    for _ in range(5):
        state, m = funcs.step_fn(state, None, batch)
    assert compute(m)["loss"] < first


# --- sliding window -----------------------------------------------------------


@pytest.mark.fast
def test_window_matches_masked_reference(rng):
    """Flash sliding window == dense attention with an explicit band mask."""
    import jax.numpy as jnp

    from tpu_parallel.models.layers import causal_attention

    b, s, h, d = 1, 256, 2, 32
    ks = jax.random.split(rng, 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)
    for window in (32, 64, 100):
        out = flash_attention(
            q, k, v, block_q=64, block_k=64, window=window, interpret=True
        )
        ref = causal_attention(q, k, v, window=window)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"window={window}",
        )


@pytest.mark.fast
def test_window_gradients_match(rng):
    from tpu_parallel.models.layers import causal_attention

    b, s, h, d = 1, 128, 2, 16
    ks = jax.random.split(rng, 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)

    def loss_flash(q, k, v):
        return (
            flash_attention(
                q, k, v, block_q=32, block_k=32, window=48, interpret=True
            )
            ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (causal_attention(q, k, v, window=48) ** 2).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, bb, name in zip(g_f, g_r, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=5e-3, atol=5e-3,
            err_msg=f"d{name}",
        )


def test_window_decode_matches_train_forward(rng):
    """A windowed model decodes with the same logits its training forward
    produces (the decode mask must apply the same band)."""
    from tpu_parallel.models import GPTLM, tiny_test

    cfg = tiny_test(dtype=jnp.float32, remat=False, attn_window=8, seq_len=32)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 20), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]
    full = model.apply({"params": params}, prompt, train=False)
    decoded, _ = model.apply(
        {"params": params}, prompt, train=False, decode=True, mutable=["cache"]
    )
    np.testing.assert_allclose(
        np.asarray(decoded), np.asarray(full), rtol=1e-4, atol=1e-4
    )


# --- grouped-query attention (native: K/V never expanded) ---------------------


def _gqa_ref(q, k, v, segment_ids=None):
    """Expand K/V heads and run the dense reference — GQA ground truth."""
    group = q.shape[2] // k.shape[2]
    ke = jnp.repeat(k, group, axis=2)
    ve = jnp.repeat(v, group, axis=2)
    return reference_attention(
        q.transpose(0, 2, 1, 3),
        ke.transpose(0, 2, 1, 3),
        ve.transpose(0, 2, 1, 3),
        segment_ids=segment_ids,
    ).transpose(0, 2, 1, 3)


def _make_gqa(rng, b=2, s=256, h=4, h_kv=2, d=32, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    q = jax.random.normal(ks[0], (b, s, h, d), dtype)
    k = jax.random.normal(ks[1], (b, s, h_kv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, h_kv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("stream", [False, True])
def test_gqa_forward_matches_expanded_reference(rng, stream):
    for h, h_kv in ((4, 2), (4, 1), (6, 3)):
        q, k, v = _make_gqa(rng, h=h, h_kv=h_kv)
        out = flash_attention(
            q, k, v, block_q=64, block_k=64, interpret=True, stream=stream
        )
        ref = _gqa_ref(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"h={h} h_kv={h_kv} stream={stream}",
        )


@pytest.mark.parametrize("stream", [False, True])
def test_gqa_gradients_match_expanded_reference(rng, stream):
    q, k, v = _make_gqa(rng, b=1, s=128, h=4, h_kv=2, d=32)

    def loss_flash(q, k, v):
        return (
            flash_attention(
                q, k, v, block_q=64, block_k=64, interpret=True, stream=stream
            )
            ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (_gqa_ref(q, k, v) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
            err_msg=f"d{name} mismatch (stream={stream})",
        )


def test_gqa_packed_window_matches_reference(rng):
    """GQA composes with segment ids and sliding window in-kernel."""
    from tpu_parallel.models.layers import causal_attention

    q, k, v = _make_gqa(rng, b=2, s=128, h=4, h_kv=2, d=32)
    seg = _packed_segments(jax.random.PRNGKey(7), 2, 128)
    out = flash_attention(
        q, k, v, segment_ids=seg, block_q=64, block_k=64, interpret=True
    )
    ref = _gqa_ref(q, k, v, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)
    # window (no segments)
    out_w = flash_attention(
        q, k, v, block_q=32, block_k=32, window=48, interpret=True
    )
    group = 2
    ref_w = causal_attention(
        q, jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2), window=48
    )
    np.testing.assert_allclose(
        np.asarray(out_w), np.asarray(ref_w), rtol=2e-3, atol=2e-3
    )


def test_gqa_model_flash_matches_xla(rng):
    """A GQA model forward agrees between attn_impl='flash' and 'xla'."""
    from tpu_parallel.models import GPTLM, tiny_test

    cfg_x = tiny_test(
        n_kv_heads=2, dtype=jnp.float32, remat=False, scan_layers=False,
        seq_len=64, attn_impl="xla",
    )
    cfg_f = tiny_test(
        n_kv_heads=2, dtype=jnp.float32, remat=False, scan_layers=False,
        seq_len=64, attn_impl="flash", flash_block_q=32, flash_block_k=32,
    )
    tokens = jax.random.randint(rng, (2, 64), 0, cfg_x.vocab_size)
    params = GPTLM(cfg_x).init({"params": jax.random.PRNGKey(0)}, tokens, train=False)[
        "params"
    ]
    lx = GPTLM(cfg_x).apply({"params": params}, tokens, train=False)
    lf = GPTLM(cfg_f).apply({"params": params}, tokens, train=False)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lx), rtol=2e-3, atol=2e-3)


def test_gqa_decode_matches_train_forward(rng):
    """GQA prefill-decode (kv-width cache, grouped einsum) == train forward."""
    from tpu_parallel.models import GPTLM, tiny_test

    cfg = tiny_test(n_kv_heads=2, dtype=jnp.float32, remat=False, seq_len=32)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 20), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]
    full = model.apply({"params": params}, prompt, train=False)
    decoded, _ = model.apply(
        {"params": params}, prompt, train=False, decode=True, mutable=["cache"]
    )
    np.testing.assert_allclose(
        np.asarray(decoded), np.asarray(full), rtol=1e-4, atol=1e-4
    )


# --- streamed (long-sequence) kernels ----------------------------------------


@pytest.mark.parametrize("window", [0, 100])
def test_stream_forward_matches_resident(rng, window):
    q, k, v = _make_qkv(rng, b=1, s=256, h=2, d=32)
    out_r = flash_attention(
        q, k, v, block_q=64, block_k=64, window=window, interpret=True,
        stream=False,
    )
    out_s = flash_attention(
        q, k, v, block_q=64, block_k=64, window=window, interpret=True,
        stream=True,
    )
    np.testing.assert_allclose(
        np.asarray(out_s), np.asarray(out_r), rtol=1e-5, atol=1e-5
    )


def test_stream_packed_matches_reference(rng):
    q, k, v = _make_qkv(rng, b=2, s=256)
    seg = _packed_segments(jax.random.PRNGKey(9), 2, 256)
    out = flash_attention(
        q, k, v, segment_ids=seg, block_q=64, block_k=64, interpret=True,
        stream=True,
    )
    ref = reference_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        segment_ids=seg,
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("window", [0, 48])
def test_stream_gradients_match_resident(rng, window):
    q, k, v = _make_qkv(rng, b=1, s=128, h=2, d=32)

    def loss(stream):
        def f(q, k, v):
            return (
                flash_attention(
                    q, k, v, block_q=32, block_k=32, window=window,
                    interpret=True, stream=stream,
                )
                ** 2
            ).sum()

        return f

    g_s = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_s, g_r, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
            err_msg=f"d{name} (window={window})",
        )


def test_stream_chunk_attention_combines(rng):
    """flash_chunk_attention's streamed path (non-causal full chunks)."""
    from tpu_parallel.ops.flash_attention import flash_chunk_attention

    q, k, v = _make_qkv(rng, b=1, s=128, h=2, d=32)
    out_r, lse_r = flash_chunk_attention(
        q, k, v, causal=False, block_q=64, block_k=64, interpret=True,
        stream=False,
    )
    out_s, lse_s = flash_chunk_attention(
        q, k, v, causal=False, block_q=64, block_k=64, interpret=True,
        stream=True,
    )
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse_s), np.asarray(lse_r), rtol=1e-5, atol=1e-5)


def test_stream_auto_dispatch_long_seq(rng):
    """seq 8192 > STREAM_SEQ_THRESHOLD auto-selects the streamed kernels and
    fwd+bwd stay correct (spot-checked against the dense reference on a
    slice-able size is impractical at 8k; instead check self-consistency of
    the online softmax: output rows equal a direct jnp computation on a few
    sampled query positions)."""
    b, s, h, d = 1, 8192, 1, 64
    ks = jax.random.split(rng, 3)
    q, k, v = (
        jax.random.normal(kk, (b, s, h, d), jnp.float32) * 0.1 for kk in ks
    )
    out = flash_attention(q, k, v, block_q=512, block_k=512, interpret=True)

    # dense ground truth at a handful of query positions
    for pos in (0, 511, 4096, 8191):
        qi = q[:, pos, 0]  # [b, d]
        scores = jnp.einsum("bd,bkd->bk", qi, k[:, : pos + 1, 0]) / jnp.sqrt(d)
        probs = jax.nn.softmax(scores, axis=-1)
        ref = jnp.einsum("bk,bkd->bd", probs, v[:, : pos + 1, 0])
        np.testing.assert_allclose(
            np.asarray(out[:, pos, 0]), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"pos={pos}",
        )


def test_stream_long_seq_backward_runs(rng):
    """fwd+bwd at seq 8192 through the streamed kernels (grads finite)."""
    b, s, h, d = 1, 8192, 1, 64
    ks = jax.random.split(rng, 3)
    q, k, v = (
        jax.random.normal(kk, (b, s, h, d), jnp.float32) * 0.1 for kk in ks
    )

    def loss(q, k, v):
        return (
            flash_attention(q, k, v, block_q=512, block_k=512, interpret=True)
            ** 2
        ).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, name in ((gq, "dq"), (gk, "dk"), (gv, "dv")):
        arr = np.asarray(g)
        assert np.isfinite(arr).all(), f"{name} has non-finite entries"
        assert np.abs(arr).max() > 0, f"{name} is all zero"


@pytest.mark.parametrize("q_offset", [32, 100, 140, -32, -100, -140])
def test_stream_offset_chunk_matches_resident(rng, q_offset):
    """Streamed kernels with a window q_offset (ring partial chunks) agree
    with the resident kernels — including empty rows (at q_offset=140 with
    window=40, rows past local index 26 see no keys at all: their partials
    must come back (0, NEG_INF) with exactly-zero gradients).  NEGATIVE
    offsets are the bidirectional ring's ahead chunks: the in-bounds
    clamps in the streamed index maps must hold there too (early q blocks
    see no keys; late k blocks see no queries)."""
    from tpu_parallel.ops.flash_attention import flash_chunk_attention

    b, s, h, d = 1, 128, 2, 32
    ks = jax.random.split(rng, 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)
    window = 40

    def run(stream):
        def f(q, k, v):
            out, lse = flash_chunk_attention(
                q, k, v, causal=False, window=window, q_offset=q_offset,
                block_q=32, block_k=32, interpret=True, stream=stream,
            )
            return out, lse

        (out, lse), vjp = jax.vjp(f, q, k, v)
        grads = vjp((jnp.ones_like(out), jnp.ones_like(lse) * 0.1))
        return out, lse, grads

    out_r, lse_r, g_r = run(False)
    out_s, lse_s, g_s = run(True)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse_s), np.asarray(lse_r), rtol=1e-5, atol=1e-5)
    for a, b_, name in zip(g_s, g_r, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-5,
            err_msg=f"d{name} (q_offset={q_offset})",
        )


@pytest.mark.fast
def test_remat_policy_sees_kernel_outputs(rng):
    """The finalize-pattern contract: the fwd kernels' out/lse are ordinary
    named jaxpr values, so a save_only_these_names("attn") remat policy
    keeps them and the backward graph contains NO forward-kernel re-run —
    3 pallas calls (fwd + dq + dkv), not 4.  Guards against re-hiding the
    forward inside the custom_vjp or dropping the checkpoint_name calls,
    for both the self-attention path and the chunk (ring/encoder) path."""
    from tpu_parallel.ops.flash_attention import flash_chunk_attention

    q, k, v = _make_qkv(rng, b=1, s=64, h=1, d=16)
    pol_save = jax.checkpoint_policies.save_only_these_names("attn")
    pol_none = jax.checkpoint_policies.save_only_these_names("nothing-matches")

    def chunk_block(q, k, v):
        out, lse = flash_chunk_attention(
            q, k, v, causal=True, block_q=32, block_k=32, interpret=True
        )
        return (out * 2).sum() + (lse * 0.1).sum()

    def self_block(q, k, v):
        out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
        return (out * 2).sum()

    for name, block in (("chunk", chunk_block), ("self", self_block)):
        counts = {}
        for pname, pol in (("saved", pol_save), ("unsaved", pol_none)):
            f = jax.checkpoint(block, policy=pol, prevent_cse=True)
            text = str(jax.make_jaxpr(jax.grad(f))(q, k, v))
            counts[pname] = text.count("pallas_call")
        assert counts["saved"] == 3, (name, counts)
        assert counts["unsaved"] == 4, (name, counts)
