"""FSDP / ZeRO-3 parameter-sharding tests on the 8-device CPU mesh."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import Batch, TrainState, compute
from tpu_parallel.core.losses import make_classification_loss
from tpu_parallel.data import classification_batch
from tpu_parallel.models import MLPClassifier, MLPConfig
from tpu_parallel.parallel import dp, fsdp
from tpu_parallel.parallel.spmd import build_train_functions
from tpu_parallel.runtime import MeshConfig, make_mesh

IN_DIM = 32
CFG = MLPConfig(hidden_size=64, num_classes=10, dropout_rate=0.0, dtype=jnp.float32)


def _fsdp_model(min_weight_size=0):
    wrapper = lambda cls: fsdp.shard_module_params(
        cls, axis_name="data", min_weight_size=min_weight_size
    )
    return MLPClassifier(CFG, dense_wrapper=wrapper)


def _make_init(model):
    from tpu_parallel.parallel.spmd import make_model_init

    return make_model_init(model, optax.adamw(1e-3))


def test_params_are_sharded(mesh_data8, rng):
    model = _fsdp_model()
    batch = classification_batch(jax.random.PRNGKey(0), 64, IN_DIM, 10)
    funcs = build_train_functions(
        _make_init(model),
        make_classification_loss("data"),
        mesh_data8,
        batch,
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    # hidden kernel (32, 64): largest dim 64 divisible by 8 -> sharded to (32, 8)
    kernel = state.params["hidden_0"]["kernel"]
    assert isinstance(kernel, nn.Partitioned)
    spec = nn.get_partition_spec(state).params["hidden_0"]["kernel"]
    assert "data" in spec
    # global view: full logical shape; addressable shards are 1/8 slices
    assert kernel.value.shape == (IN_DIM, 64)
    shard_shapes = {s.data.shape for s in kernel.value.addressable_shards}
    assert shard_shapes == {(IN_DIM, 8)}
    # optimizer state mirrors the partitioning
    mu_kernel = state.opt_state[0].mu["hidden_0"]["kernel"]
    assert isinstance(mu_kernel, nn.Partitioned)


def test_fsdp_loss_decreases(mesh_data8, rng):
    model = _fsdp_model()
    batch = classification_batch(jax.random.PRNGKey(0), 128, IN_DIM, 10)
    funcs = build_train_functions(
        _make_init(model),
        make_classification_loss("data"),
        mesh_data8,
        batch,
        num_minibatches=4,
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)["loss"]
    for _ in range(15):
        state, m = funcs.step_fn(state, None, batch)
    assert compute(m)["loss"] < first


def test_fsdp_matches_dp(mesh_data8, rng):
    """FSDP-sharded training must be numerically identical to replicated DP."""
    batch = classification_batch(jax.random.PRNGKey(1), 64, IN_DIM, 10)
    loss_fn = make_classification_loss("data")

    model_fsdp = _fsdp_model()
    funcs = build_train_functions(
        _make_init(model_fsdp), loss_fn, mesh_data8, batch, donate=False
    )
    state_f = funcs.init_fn(rng, batch)

    model_dp = MLPClassifier(CFG)
    init_dp_fn = dp.make_init(
        lambda r, x: _make_init(model_dp)(r, Batch(inputs=x, labels=jnp.zeros(x.shape[0], jnp.int32))),
        mesh=mesh_data8,
    )
    state_d = init_dp_fn(rng, batch.inputs)
    step_dp = dp.make_train_step(loss_fn, num_minibatches=1, mesh=mesh_data8, donate=False)

    for _ in range(3):
        state_f, m_f = funcs.step_fn(state_f, None, batch)
        state_d, m_d = step_dp(state_d, None, batch)

    # gather the FSDP params to full shape and compare against DP's replicas
    full_f = jax.device_get(
        jax.tree_util.tree_map(
            lambda x: x.value if isinstance(x, nn.Partitioned) else x,
            state_f.params,
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )
    )
    full_d = jax.device_get(state_d.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5), full_f, full_d
    )
    assert compute(m_f)["loss"] == pytest.approx(compute(m_d)["loss"], rel=1e-4)


def test_min_weight_size_keeps_small_params_replicated(mesh_data8, rng):
    model = _fsdp_model(min_weight_size=2**18)  # everything below threshold
    batch = classification_batch(jax.random.PRNGKey(0), 64, IN_DIM, 10)
    funcs = build_train_functions(
        _make_init(model),
        make_classification_loss("data"),
        mesh_data8,
        batch,
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    leaves = jax.tree_util.tree_leaves(
        state.params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )
    assert not any(isinstance(l, nn.Partitioned) for l in leaves)


def test_sync_gradients_partition_aware(mesh_data8):
    """Partitioned grads keep per-shard values; replicated grads get pmean'd."""

    def body(x):
        grads = {
            "sharded": nn.Partitioned(
                x * jax.lax.axis_index("data"), names=("data",)
            ),
            "replicated": x * jax.lax.axis_index("data").astype(jnp.float32),
        }
        out = fsdp.sync_gradients(grads, ("data",))
        return out["sharded"].value, out["replicated"]

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh_data8,
            in_specs=P(),
            out_specs=(P("data"), P()),
            check_vma=False,
        )
    )
    sharded, replicated = f(jnp.ones(1))
    # sharded: untouched per-device values 0..7
    np.testing.assert_allclose(np.asarray(sharded).ravel(), np.arange(8.0))
    # replicated: mean of 0..7 = 3.5
    np.testing.assert_allclose(np.asarray(replicated), [3.5])
