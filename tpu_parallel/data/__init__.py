from tpu_parallel.data.synthetic import classification_batch, lm_batch

__all__ = ["classification_batch", "lm_batch"]
