"""One serving replica as the cluster sees it: an engine plus health.

A production cluster never talks to a :class:`~tpu_parallel.serving.engine.
ServingEngine` directly — it talks to a :class:`ReplicaHandle`, which adds
the things scale-out needs on top of the engine's tick loop:

- **Health state** (the full lifecycle is ``healthy`` / ``degraded`` /
  ``dead`` / ``backoff`` / ``probation`` — docs/12_cluster.md draws the
  machine): routers skip dead and backing-off replicas outright and
  deprioritize degraded (stalled) ones; the frontend retries a dead
  replica's in-flight work elsewhere.  ANY exception escaping
  ``engine.step()`` marks the replica dead — a replica that throws
  mid-tick has an engine in an unknown state, and the only safe move is
  to stop routing to it and replay its work.  DEGRADED is set by the
  frontend's progress WATCHDOG (observed no-progress), never by fault
  injection itself — detection is decoupled from injection.
- **Restart** (:meth:`restart` + :class:`RestartPolicy`): a dead replica
  whose handle carries an ``engine_factory`` can be rebuilt from the
  shared model/params.  The frontend schedules the rebuild with
  exponential backoff (``backoff`` state) and re-enters the fresh engine
  through a half-open ``probation`` state before trusting it with full
  traffic again — the circuit-breaker shape.
- **Load accounting**: queue depth + active slots + estimated pending
  prefill tokens, combined into one comparable ``load()`` scalar (the
  least-loaded router's sort key).  Everything is host-side bookkeeping
  the engine already tracks — reading load never touches the device.
- **Fault injection** (:class:`FaultPlan`): deterministic crash / stall /
  crash-loop / admission-reject faults keyed on the replica's own tick
  count, so failover tests replay EXACTLY (crash on tick 7 is crash on
  tick 7, every run).  A ``FaultPlan`` is how the acceptance suite proves
  the bitwise-exactness-under-failure story without flaky process
  killing.  Injection only causes BEHAVIOR (a raised exception, a no-op
  tick, a closed admission gate); it never edits health — the watchdog
  and the frontend's death handling own every health transition.

The handle also keeps the replica-local request ledger (every submitted,
not-yet-terminal engine :class:`RequestOutput`): when the replica dies,
``orphans()`` is precisely the work the frontend must re-route (and then
``forget()``, so a restarted replica can never double-replay them).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional

from tpu_parallel.serving.engine import ServingEngine
from tpu_parallel.serving.request import Request, RequestOutput

# replica health states (the lifecycle ring: healthy -> degraded ->
# dead -> backoff -> probation -> healthy; docs/12_cluster.md)
HEALTHY = "healthy"  # routable
DEGRADED = "degraded"  # stalled/slow: routable only when nothing healthy is
DEAD = "dead"  # never routable; in-flight work must be replayed elsewhere
BACKOFF = "backoff"  # dead with a restart scheduled; never routable
PROBATION = "probation"  # restarted, half-open: routable under a request cap
RETIRED = "retired"  # scaled down (autopilot): removed from the fleet, idle

# ``load()`` weight of one pending prefill token relative to one queued
# request / one active slot: a slot decodes one token per tick while a
# queued prompt costs its whole length in prefill work, so tokens are
# discounted to rough slot-tick equivalents (64 prompt tokens ~ one
# request's worth of near-term work).  The constant only needs to rank
# replicas consistently, not model latency.
PREFILL_TOKEN_WEIGHT = 1.0 / 64.0


def xla_like_error(tick: int) -> Exception:
    """An ``exception_factory`` shaped like a real accelerator failure
    (the RuntimeError class XLA raises on device loss / deadline)."""
    return RuntimeError(
        f"XLA:TPU RESOURCE_EXHAUSTED: device halted at tick {tick} "
        "(simulated)"
    )


def logic_error(tick: int) -> Exception:
    """An ``exception_factory`` shaped like a host-side bug — a distinct
    exception TYPE from :func:`xla_like_error`, so tests can pin that the
    death path preserves the cause regardless of what escaped."""
    return ValueError(f"corrupt slot bookkeeping at tick {tick} (simulated)")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Deterministic fault schedule keyed on the replica's OWN tick count
    (the number of ``step()`` calls it has served — LIFETIME ticks keep
    counting across restarts; ``crash_every`` keys on INCARNATION ticks,
    the count since the last restart).

    - ``crash_at_tick``: the first step at/after this index raises
      :class:`ReplicaDead` instead of running — the engine is abandoned
      mid-flight exactly as a process kill would leave it.  One-shot: a
      restarted replica does not re-crash on the same schedule (use
      ``crash_every`` for a crash-loop).
    - ``crash_every``: the flapping shape — EVERY incarnation crashes on
      its ``crash_every``-th step, so a replica with a restart budget
      enters a crash-loop until the frontend's circuit breaker gives up.
    - ``exception_factory``: called with the crashing tick to build the
      exception the "engine" died of (e.g. :func:`xla_like_error` vs
      :func:`logic_error`); None raises a plain :class:`ReplicaDead`.
      Excluded from equality — schedules compare by their timing.
    - ``stall_at_tick`` + ``stall_ticks``: steps in
      ``[stall_at_tick, stall_at_tick + stall_ticks)`` do nothing (no
      engine tick, no events) — the GC-pause / preemption shape.  The
      stall does NOT touch health: detecting it from observed
      no-progress is the frontend watchdog's job.
    - ``reject_at_tick`` + ``reject_ticks``: during that tick window the
      replica refuses NEW admissions (``accepting`` is False) while
      in-flight work proceeds — the overload-shedding shape.
    - ``swap_at_tick``: an OPERATOR event, not a fault: the chaos/bench
      harness reading the plan calls ``Frontend.begin_swap`` when the
      fleet reaches this tick (``swap@T``), so seeded storms exercise a
      rolling weight swap colliding with crashes and stalls.  The plan
      itself never triggers it — like every other entry it only
      describes the schedule; the harness owns the behavior.
    """

    crash_at_tick: Optional[int] = None
    stall_at_tick: Optional[int] = None
    stall_ticks: int = 0
    reject_at_tick: Optional[int] = None
    reject_ticks: int = 0
    crash_every: Optional[int] = None
    swap_at_tick: Optional[int] = None
    exception_factory: Optional[Callable[[int], Exception]] = (
        dataclasses.field(default=None, compare=False)
    )

    def crash_scheduled(self, tick: int) -> bool:
        """The one-shot crash window opened (the handle tracks firing)."""
        return self.crash_at_tick is not None and tick >= self.crash_at_tick

    def flap_scheduled(self, incarnation_tick: int) -> bool:
        """This incarnation reached its crash-loop step."""
        return (
            self.crash_every is not None
            and incarnation_tick + 1 >= self.crash_every
        )

    def stalled(self, tick: int) -> bool:
        return (
            self.stall_at_tick is not None
            and self.stall_at_tick <= tick < self.stall_at_tick + self.stall_ticks
        )

    def rejecting(self, tick: int) -> bool:
        return (
            self.reject_at_tick is not None
            and self.reject_at_tick
            <= tick
            < self.reject_at_tick + self.reject_ticks
        )

    @classmethod
    def from_seed(
        cls,
        rnd: "random.Random",
        ticks: int,
        kinds: Optional[tuple] = None,
    ) -> "FaultPlan":
        """Draw a randomized-but-reproducible schedule over a ``ticks``
        horizon from a seeded :class:`random.Random` — the chaos
        harness's constructor.  ``kinds`` pins which fault shapes appear
        (subset of ``crash`` / ``stall`` / ``flap`` / ``reject``); None
        draws a random subset.  Same rng state => identical plan
        (``test_fault_plan_from_seed_deterministic``).

        A drawn stall always ENDS before a drawn crash begins, so the
        stall is observable (a crashed replica can't stall).  Determinism
        is per (rng state, ticks, kinds) triple: each kind's draws only
        happen when that kind is selected, so plans ARE expected to
        differ across different ``kinds`` combinations from one seed.
        """
        if ticks < 8:
            raise ValueError(f"ticks={ticks} < 8: no room for a schedule")
        if kinds is None:
            pool = ("crash", "stall", "flap", "reject")
            kinds = tuple(k for k in pool if rnd.random() < 0.5)
        unknown = set(kinds) - {"crash", "stall", "flap", "reject", "swap"}
        if unknown:
            raise ValueError(f"unknown fault kinds {sorted(unknown)}")
        kw: dict = {}
        if "swap" in kinds:
            # early enough that the rollout collides with the storm, late
            # enough that traffic and faults are already in motion
            kw["swap_at_tick"] = rnd.randrange(3, max(4, ticks // 2))
        if "stall" in kinds:
            kw["stall_at_tick"] = rnd.randrange(2, max(3, ticks // 3))
            kw["stall_ticks"] = rnd.randrange(2, 6)
        if "reject" in kinds:
            kw["reject_at_tick"] = rnd.randrange(1, max(2, ticks // 2))
            kw["reject_ticks"] = rnd.randrange(1, 8)
        if "crash" in kinds:
            # crash strictly after any stall window so the stall is seen
            floor = kw.get("stall_at_tick", 0) + kw.get("stall_ticks", 0) + 2
            kw["crash_at_tick"] = floor + rnd.randrange(
                0, max(2, ticks // 2)
            )
        if "flap" in kinds:
            kw["crash_every"] = rnd.randrange(6, max(7, ticks // 2))
        if ("crash" in kinds or "flap" in kinds) and rnd.random() < 0.5:
            kw["exception_factory"] = (
                xla_like_error if rnd.random() < 0.5 else logic_error
            )
        return cls(**kw)


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    """How (and how hard) the frontend tries to revive dead replicas —
    the circuit-breaker knobs (docs/12_cluster.md draws the lifecycle).

    - ``max_restarts``: lifetime restart attempts per replica.  Past it
      the breaker stays OPEN: the replica is dead forever (pre-PR-8
      behavior).
    - ``backoff_seconds`` * ``backoff_factor`` ** (consecutive failures
      - 1), capped at ``max_backoff_seconds``: the delay between a death
      and the restart attempt, measured on the frontend's INJECTABLE
      clock (``scripts/check_clock.py`` keeps it that way).  Consecutive
      failures reset on a probation promotion — a replica that proved
      itself healthy earns back a fast restart.
    - ``probation_ticks``: clean cluster ticks a restarted replica must
      serve half-open before promotion to HEALTHY.  A tick only counts
      as clean if it is exception-free AND not stall-suspect (a replica
      with work that shows no observable progress earns nothing — a
      wedged restart is the watchdog's to kill, never promoted).
    - ``probation_requests``: max CONCURRENT open requests routable to a
      probation replica — the half-open trickle that proves the engine
      without betting real traffic on it.
    """

    max_restarts: int = 3
    backoff_seconds: float = 1.0
    backoff_factor: float = 2.0
    max_backoff_seconds: float = 60.0
    probation_ticks: int = 8
    probation_requests: int = 1

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError(f"max_restarts={self.max_restarts} < 0")
        if self.backoff_seconds < 0:
            raise ValueError(f"backoff_seconds={self.backoff_seconds} < 0")
        if self.backoff_factor < 1.0:
            raise ValueError(f"backoff_factor={self.backoff_factor} < 1")
        if self.probation_ticks < 1:
            raise ValueError(f"probation_ticks={self.probation_ticks} < 1")
        if self.probation_requests < 1:
            raise ValueError(
                f"probation_requests={self.probation_requests} < 1"
            )

    def delay(self, failures: int) -> float:
        """Backoff before the next restart after ``failures`` consecutive
        failures (>= 1): exponential, capped."""
        exponent = max(0, failures - 1)
        return min(
            self.backoff_seconds * self.backoff_factor ** exponent,
            self.max_backoff_seconds,
        )


class ReplicaDead(RuntimeError):
    """Raised by ``ReplicaHandle.step()`` when the replica dies — by
    FaultPlan schedule or by a real exception escaping the engine tick.
    The frontend catches it, collects ``orphans()``, and re-routes."""

    def __init__(self, replica_id: int, cause: Optional[str] = None):
        super().__init__(
            f"replica {replica_id} died"
            + (f" ({cause})" if cause else "")
        )
        self.replica_id = replica_id


class ReplicaHandle:
    """Cluster-side wrapper of one :class:`ServingEngine`.

    ``submit()``/``step()`` mirror the engine surface but maintain the
    health state, the tick counters the :class:`FaultPlan` keys off, and
    the not-yet-terminal request ledger that ``orphans()`` reports after
    a death.  The handle never constructs engines EXCEPT through the
    caller-supplied ``engine_factory`` — the caller owns model and params
    placement (same process here; the design point is that nothing in
    the cluster layer assumes it), and a factory is the caller saying
    "this is how you rebuild me".  Without one, a dead replica stays
    dead (the pre-self-healing behavior).
    """

    def __init__(
        self,
        replica_id: int,
        engine: ServingEngine,
        fault_plan: Optional[FaultPlan] = None,
        engine_factory: Optional[Callable[[], ServingEngine]] = None,
    ):
        self.replica_id = replica_id
        self.engine = engine
        self.fault_plan = fault_plan
        self.engine_factory = engine_factory
        self.health = HEALTHY
        # rolling weight swap: True while this replica is the rollout's
        # current target being drained of traffic — the frontend's
        # dispatch filter skips it for NEW placement while in-flight work
        # finishes on the old weights (cluster/swap.py owns the flag)
        self.swap_excluded = False
        self.ticks = 0  # lifetime step() calls, NEVER reset
        self.incarnation_ticks = 0  # step() calls since the last restart
        self.restarts = 0  # successful restarts served so far
        self._crash_fired = False  # one-shot crash_at_tick bookkeeping
        self.cause_of_death: Optional[str] = None  # set by kill()
        # KV blocks warm-started into this replica's prefix cache at
        # scale-up (cluster/migration.py; 0 = cold or no radix cache)
        self.kv_warm_blocks = 0
        # engine integrity_trips watermark: a tick that trips the
        # NaN/Inf sentinel escalates this replica to DEGRADED health
        self._integrity_seen = 0
        # engine request_id -> live engine RequestOutput; pruned as
        # requests reach a terminal state
        self._ledger: Dict[str, RequestOutput] = {}

    # -- load signals ------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self.engine.scheduler.depth

    @property
    def active_slots(self) -> int:
        return self.engine.in_flight

    @property
    def pending_prefill_tokens(self) -> int:
        return self.engine.pending_prefill_tokens

    @property
    def weights_version(self) -> str:
        """The served weight set's identity (``"initial"`` until a hot
        swap rebinds it) — what the rolling-swap status and the
        ``cluster_swap_version`` gauge report."""
        return getattr(self.engine, "weights_version", "initial")

    @property
    def open_requests(self) -> int:
        """Submitted, not-yet-terminal requests on this replica — the
        probation concurrency cap's denominator."""
        self._prune()
        return len(self._ledger)

    def load(self) -> float:
        """One comparable scalar: queued requests + occupied slots +
        discounted pending prefill tokens (see ``PREFILL_TOKEN_WEIGHT``).
        A dead or backing-off replica reports infinite load so any
        ranking consumer that forgets to filter by health still never
        picks it."""
        if self.health in (DEAD, BACKOFF, RETIRED):
            return float("inf")
        return (
            self.queue_depth
            + self.active_slots
            + self.pending_prefill_tokens * PREFILL_TOKEN_WEIGHT
        )

    @property
    def routable(self) -> bool:
        """Placeable for frontend dispatch: alive (healthy, degraded or
        on probation) and not inside a FaultPlan admission-reject
        window.  Deliberately IGNORES the engine's drain gate — frontend
        dispatch relocates already-accepted work (``requeue=True``),
        which the gate waves through; a draining cluster must still be
        able to land its re-routed queue remainders.  The probation
        request cap is the FRONTEND's filter (it owns the policy), not
        this property's."""
        if self.health in (DEAD, BACKOFF, RETIRED):
            return False
        if self.fault_plan is not None and self.fault_plan.rejecting(
            self.ticks
        ):
            return False
        return True

    @property
    def accepting(self) -> bool:
        """Accepting NEW admissions: routable AND not drain-gated."""
        return self.routable and not self.engine.draining

    # -- work --------------------------------------------------------------

    def submit(
        self,
        request: Request,
        requeue: bool = False,
        arrival_time: Optional[float] = None,
    ) -> RequestOutput:
        """Hand one request to the replica's engine; tracks it in the
        ledger unless the engine rejected it synchronously."""
        if self.health in (DEAD, BACKOFF):
            raise ReplicaDead(
                self.replica_id, f"submit to {self.health} replica"
            )
        out = self.engine.add_request(
            request, requeue=requeue, arrival_time=arrival_time
        )
        if not out.done:
            self._ledger[request.request_id] = out
        return out

    def step(self) -> list:
        """One engine tick under the fault plan.  Raises
        :class:`ReplicaDead` on a scheduled crash or any engine exception
        (health flips to DEAD first, so the raiser's view and a later
        reader's view agree); returns the tick's StreamEvents, or [] for
        a stalled tick.  A stall produces BEHAVIOR only (no events, no
        engine tick) — whether that makes the replica DEGRADED is the
        frontend watchdog's call, from observation."""
        if self.health in (DEAD, BACKOFF):
            raise ReplicaDead(
                self.replica_id, f"step on {self.health} replica"
            )
        tick = self.ticks
        itick = self.incarnation_ticks
        self.ticks += 1
        self.incarnation_ticks += 1
        fp = self.fault_plan
        if fp is not None:
            cause = None
            if not self._crash_fired and fp.crash_scheduled(tick):
                self._crash_fired = True
                cause = f"fault plan, tick {tick}"
            elif fp.flap_scheduled(itick):
                cause = (
                    f"fault plan crash-loop, incarnation tick {itick}"
                )
            if cause is not None:
                self.health = DEAD
                if fp.exception_factory is not None:
                    exc = fp.exception_factory(tick)
                    raise ReplicaDead(
                        self.replica_id, repr(exc)
                    ) from exc
                raise ReplicaDead(self.replica_id, cause)
            if fp.stalled(tick):
                return []
        try:
            events = self.engine.step()
        except Exception as exc:  # engine state unknown: replica is gone
            self.health = DEAD
            raise ReplicaDead(self.replica_id, repr(exc)) from exc
        trips = getattr(self.engine, "integrity_trips", 0)
        if trips > self._integrity_seen:
            # the NaN/Inf sentinel tripped this tick: the affected
            # request already FAILED typed; the replica escalates to
            # DEGRADED so routers deprioritize an engine producing
            # non-finite logits (the watchdog restores HEALTHY if
            # subsequent work progresses cleanly — an escalation, not
            # a death sentence)
            self._integrity_seen = trips
            if self.health == HEALTHY:
                self.health = DEGRADED
        self._prune()
        return events

    def retire(self) -> None:
        """Scale-down retirement (the autopilot's shrink actuator): the
        engine's drain gate closes and health becomes RETIRED — a
        terminal state distinct from DEAD (nothing failed; no orphans,
        no restart, no breaker involvement).  The caller (the frontend)
        guarantees the idle precondition: a retiring replica holds no
        queued or in-flight work, so its cache pool is already fully
        released."""
        if self.has_work():
            raise RuntimeError(
                f"retire replica {self.replica_id} with work in flight "
                f"({self.engine.in_flight} slots, "
                f"{self.queue_depth} queued) — only idle replicas retire"
            )
        self.engine.begin_drain()
        self.health = RETIRED

    def kill(self, cause: str) -> None:
        """Declare this replica dead WITHOUT an exception — the watchdog
        path: the engine may even be fine (a false positive), but from
        the cluster's point of view a replica that stopped delivering is
        gone; its work replays elsewhere and the engine is abandoned (or
        rebuilt via :meth:`restart`)."""
        self.health = DEAD
        self.cause_of_death = cause

    def restart(self) -> None:
        """Rebuild the engine through ``engine_factory`` and re-enter
        half-open: health becomes PROBATION, the incarnation tick counter
        resets (so ``crash_every`` keys on the new life), and the ledger
        clears — every orphan was already replayed by the frontend, so a
        stale ledger would only double-replay them.  A factory exception
        propagates with the handle UNTOUCHED (still restartable); the
        frontend counts it as a failed attempt and backs off harder."""
        if self.engine_factory is None:
            raise RuntimeError(
                f"replica {self.replica_id} has no engine_factory — "
                "cannot restart"
            )
        engine = self.engine_factory()  # may raise: handle stays as-is
        self.engine = engine
        self._ledger.clear()
        self._integrity_seen = 0  # the fresh engine's counter restarts
        self.incarnation_ticks = 0
        self.restarts += 1
        self.health = PROBATION
        # a rebuilt engine is a fresh traffic target — any stale swap
        # exclusion died with the old incarnation (the swap controller
        # re-queues the replica as a target if its rollout still runs)
        self.swap_excluded = False

    def has_work(self) -> bool:
        return (
            self.health not in (DEAD, BACKOFF, RETIRED)
            and self.engine.has_work()
        )

    def _prune(self) -> None:
        done = [rid for rid, out in self._ledger.items() if out.done]
        for rid in done:
            del self._ledger[rid]

    def orphans(self) -> List[RequestOutput]:
        """Every tracked request that had NOT reached a terminal state —
        queued or holding a slot — in submission order.  After a death
        this is exactly the work the frontend replays elsewhere (tokens
        already delivered ride along on each RequestOutput, so the replay
        can force-prefix them)."""
        self._prune()
        return list(self._ledger.values())

    def forget(self, request_id: str) -> None:
        """Drop one request from the ledger (the frontend pulled it back
        for re-routing — e.g. a drain's queued remainder)."""
        self._ledger.pop(request_id, None)

    def export_kv(self, engine_rid: str):
        """Best-effort KV export of a live attempt's written prefix (the
        cross-replica migration capture — ``cluster/migration.py``).
        None whenever nothing can or should be read: a dead/backing-off
        replica's engine is in an unknown state, a fixed-slot engine has
        no block pool, and any exception during the export degrades to
        the proven recompute path rather than failing the relocation."""
        if self.health in (DEAD, BACKOFF):
            return None
        try:
            return self.engine.export_prefix(engine_rid)
        except Exception:
            return None  # capture is an optimization, never a new fault

    def take_queued(self) -> List[RequestOutput]:
        """Pull the engine's queued remainder (FIFO order) out of this
        replica for re-routing, dropping each from the ledger."""
        taken = self.engine.scheduler.take_queued()
        for out in taken:
            self.forget(out.request.request_id)
        return taken

    def summary(self) -> dict:
        dark = self.health in (DEAD, BACKOFF, RETIRED)
        return {
            "replica": self.replica_id,
            "health": self.health,
            "weights_version": self.weights_version,
            "ticks": self.ticks,
            "restarts": self.restarts,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "pending_prefill_tokens": self.pending_prefill_tokens,
            "load": None if dark else round(self.load(), 3),
        }
