"""Peer health for the fleet router: the PR 8 replica breaker,
generalized to daemons it can only observe over a wire.

The in-process frontend KNOWS when a replica died — the engine call
raised.  The fleet router only ever sees symptoms: a refused
connection, a request timeout, a 503.  So health is a per-peer state
machine fed by two evidence streams — periodic ``/healthz`` probes and
the outcome of every real request — and the states deliberately reuse
the cluster's vocabulary (docs/12_cluster.md):

- ``HEALTHY``  — routable, preferred.
- ``DEGRADED`` — recent failures (or a half-open recovery); routable
  only when no HEALTHY peer can take the key.  New evidence resolves it
  quickly in either direction.
- ``DEAD``     — ``dead_after`` consecutive failures; never routable.
  Re-probed on an exponential backoff (``reprobe_backoff_*``) so a
  rebooting host is re-admitted in seconds while a truly gone one
  costs one cheap probe per backoff cap.  A DEAD peer that answers a
  probe re-enters at DEGRADED — half-open, exactly like the replica
  breaker's probation — and earns HEALTHY with one more success.

Everything is measured on the INJECTABLE clock the constructor takes
(``scripts/check_clock.py`` walks ``tpu_parallel/fleet`` too), so the
whole fleet failure story unit-tests deterministically: tests advance a
fake clock and feed scripted probe outcomes; only ``scripts/
fleet_bench.py`` ever wires in wall time.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from tpu_parallel.cluster.replica import DEAD, DEGRADED, HEALTHY

__all__ = [
    "HEALTHY",
    "DEGRADED",
    "DEAD",
    "PeerPolicy",
    "PeerState",
    "PeerSet",
]


@dataclasses.dataclass(frozen=True)
class PeerPolicy:
    """The fleet breaker knobs (all seconds are on the injected clock).

    - ``probe_interval_seconds``: how often a live peer's ``/healthz``
      is polled.
    - ``degraded_after`` / ``dead_after``: consecutive failures that
      demote HEALTHY→DEGRADED and →DEAD.  A single success resets the
      count — one flaky probe must not start a death spiral.
    - ``reprobe_backoff_seconds`` * ``factor`` ** (deaths in a row),
      capped at ``reprobe_backoff_max``: the DEAD re-probe schedule.
    - ``connect_timeout_seconds`` / ``request_timeout_seconds``: what
      the transport should allow a probe / a unary request before
      declaring the peer unresponsive (carried here so the router and
      its transport agree without a second config object).
    - ``stream_idle_timeout_seconds``: max silence mid-stream before
      the relay treats the daemon as wedged — must comfortably exceed
      the daemon's SSE keepalive period or healthy idle streams would
      be executed for the crime of thinking.
    """

    probe_interval_seconds: float = 2.0
    degraded_after: int = 1
    dead_after: int = 3
    reprobe_backoff_seconds: float = 1.0
    reprobe_backoff_factor: float = 2.0
    reprobe_backoff_max: float = 30.0
    connect_timeout_seconds: float = 5.0
    request_timeout_seconds: float = 30.0
    stream_idle_timeout_seconds: float = 15.0

    def __post_init__(self):
        if self.degraded_after < 1:
            raise ValueError(f"degraded_after={self.degraded_after} < 1")
        if self.dead_after < self.degraded_after:
            raise ValueError(
                f"dead_after={self.dead_after} < "
                f"degraded_after={self.degraded_after}"
            )
        if self.probe_interval_seconds <= 0:
            raise ValueError("probe_interval_seconds must be positive")


class PeerState:
    """One daemon address's breaker state.  Pure bookkeeping — the
    PeerSet feeds it evidence, the router reads ``state``."""

    __slots__ = (
        "addr", "state", "failures", "consecutive_deaths", "deaths",
        "last_probe", "next_probe_at", "last_ok", "transitions",
    )

    def __init__(self, addr: str):
        self.addr = addr
        self.state = HEALTHY
        self.failures = 0  # consecutive, reset by any success
        self.consecutive_deaths = 0  # backoff escalation level
        self.deaths = 0  # lifetime DEAD transitions (metrics)
        self.last_probe = float("-inf")
        self.next_probe_at = 0.0
        self.last_ok: Optional[float] = None
        self.transitions: List[str] = []

    def routable(self) -> bool:
        return self.state != DEAD

    def note_success(self, now: float, policy: PeerPolicy) -> str:
        """Fold one success (probe or served request).  Returns the
        resulting state.  DEAD answers half-open into DEGRADED; a
        DEGRADED success completes recovery to HEALTHY."""
        self.failures = 0
        self.last_ok = now
        self.next_probe_at = now + policy.probe_interval_seconds
        if self.state == DEAD:
            self._transition(DEGRADED)
            self.consecutive_deaths = 0
        elif self.state == DEGRADED:
            self._transition(HEALTHY)
        return self.state

    def note_failure(self, now: float, policy: PeerPolicy) -> str:
        """Fold one failure (refused/timeout/transport error).  Returns
        the resulting state; entering DEAD schedules the backoff
        re-probe."""
        self.failures += 1
        if self.failures >= policy.dead_after:
            if self.state != DEAD:
                self._transition(DEAD)
                self.deaths += 1
                self.consecutive_deaths += 1
            backoff = min(
                policy.reprobe_backoff_max,
                policy.reprobe_backoff_seconds
                * policy.reprobe_backoff_factor
                ** max(0, self.consecutive_deaths - 1),
            )
            self.next_probe_at = now + backoff
        elif self.failures >= policy.degraded_after:
            if self.state == HEALTHY:
                self._transition(DEGRADED)
            self.next_probe_at = now  # verify a shaky peer promptly
        return self.state

    def probe_due(self, now: float) -> bool:
        return now >= self.next_probe_at

    def _transition(self, state: str) -> None:
        self.transitions.append(f"{self.state}->{state}")
        self.state = state

    def summary(self, now: Optional[float] = None) -> dict:
        """The breaker's ``/statez`` row.  With ``now`` (the caller's
        clock) it also reports probe RECENCY — ``last_probe_age`` is
        the operator's first stale-router tell — and when the next
        probe is due (the DEAD-backoff schedule, made visible)."""
        out = {
            "addr": self.addr,
            "state": self.state,
            "failures": self.failures,
            "deaths": self.deaths,
            "last_ok": self.last_ok,
        }
        if now is not None:
            out["last_probe_age"] = (
                None if self.last_probe == float("-inf")
                else max(0.0, now - self.last_probe)
            )
            out["next_probe_in"] = max(0.0, self.next_probe_at - now)
        return out


class PeerSet:
    """The router's membership + health view over daemon addresses.

    Not thread-safe by itself — the FleetRouter serializes access under
    its own lock; probes happen in the router's pump thread, evidence
    from request outcomes arrives from handler threads through the
    router."""

    def __init__(
        self,
        addrs: Sequence[str],
        clock: Callable[[], float],
        policy: Optional[PeerPolicy] = None,
    ):
        if not addrs:
            raise ValueError("PeerSet needs at least 1 peer address")
        self.clock = clock
        self.policy = policy or PeerPolicy()
        self.peers: Dict[str, PeerState] = {
            addr: PeerState(addr) for addr in addrs
        }
        if len(self.peers) != len(addrs):
            raise ValueError(f"duplicate peer addresses in {addrs!r}")

    def add(self, addr: str) -> PeerState:
        """Join (idempotent).  A joining peer starts DEGRADED, not
        HEALTHY: it becomes preferred only after its first good probe —
        the router must not aim traffic at an address it has never
        seen answer."""
        state = self.peers.get(addr)
        if state is None:
            state = PeerState(addr)
            state.state = DEGRADED
            self.peers[addr] = state
        return state

    def remove(self, addr: str) -> None:
        self.peers.pop(addr, None)

    def get(self, addr: str) -> Optional[PeerState]:
        return self.peers.get(addr)

    def note_success(self, addr: str) -> str:
        state = self.peers.get(addr)
        if state is None:
            return DEAD
        return state.note_success(self.clock(), self.policy)

    def note_failure(self, addr: str) -> str:
        state = self.peers.get(addr)
        if state is None:
            return DEAD
        return state.note_failure(self.clock(), self.policy)

    def routable(self) -> List[str]:
        """Addresses a new request may target, HEALTHY before DEGRADED
        (the caller applies ring order within each class)."""
        return [a for a, s in self.peers.items() if s.state == HEALTHY] + [
            a for a, s in self.peers.items() if s.state == DEGRADED
        ]

    def healthy(self) -> List[str]:
        return [a for a, s in self.peers.items() if s.state == HEALTHY]

    def probe_due(self) -> List[str]:
        now = self.clock()
        return [a for a, s in self.peers.items() if s.probe_due(now)]

    def states(self) -> Dict[str, str]:
        return {a: s.state for a, s in self.peers.items()}

    def summary(self, now: Optional[float] = None) -> dict:
        return {a: s.summary(now=now) for a, s in self.peers.items()}
