"""Tensor-parallel tests: sharded-weight math vs dense single-device reference."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import compute
from tpu_parallel.core.losses import make_classification_loss
from tpu_parallel.core.state import Batch
from tpu_parallel.data import classification_batch
from tpu_parallel.parallel import tp
from tpu_parallel.parallel.spmd import build_train_functions, make_model_init
from tpu_parallel.runtime import MeshConfig, make_mesh


def _run_tp(mesh, module_fn, x, rng, axis="model"):
    """Init + apply a TP module inside shard_map; return (params, output)."""

    def body(rng, x):
        mod = module_fn()
        variables = mod.init({"params": rng}, x)
        out = mod.apply(variables, x)
        return variables["params"], out

    probe = jax.shard_map(
        body, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False
    )
    shapes = jax.eval_shape(probe, rng, x)
    specs = nn.get_partition_spec(shapes)
    f = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P(), P()), out_specs=specs, check_vma=False
        )
    )
    return f(rng, x)


def _full(p):
    """Unbox a Partitioned param to its global value."""
    return np.asarray(p.value if isinstance(p, nn.Partitioned) else p)


def test_column_parallel_matches_dense(mesh_data4_model2, rng):
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    params, out = _run_tp(
        mesh_data4_model2,
        lambda: tp.TPDense(features=8, style="column", gather_output=True),
        x,
        rng,
    )
    kernel = _full(params["shard"]["sharded"]["kernel"])  # [tp, 16, 4]
    bias = _full(params["shard"]["sharded"]["bias"])  # [tp, 4]
    # assemble the logical [16, 8] weight: concat shards along features
    w = np.concatenate([kernel[i] for i in range(2)], axis=-1)
    b = np.concatenate([bias[i] for i in range(2)], axis=-1)
    expected = np.asarray(x) @ w + b
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-5)


def test_row_parallel_matches_dense(mesh_data4_model2, rng):
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    params, out = _run_tp(
        mesh_data4_model2,
        lambda: tp.TPDense(features=8, style="row", split_input=True),
        x,
        rng,
    )
    kernel = _full(params["shard"]["sharded"]["kernel"])  # [tp, 8, 8]
    bias = _full(params["bias"])  # [8] replicated
    w = np.concatenate([kernel[i] for i in range(2)], axis=0)  # [16, 8]
    expected = np.asarray(x) @ w + bias
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-5)


def test_tp_mlp_matches_dense(mesh_data4_model2, rng):
    """Column->gelu->row MLP == the same math with assembled full weights."""
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 12))
    params, out = _run_tp(
        mesh_data4_model2,
        lambda: tp.TPMLP(hidden_features=16, out_features=12),
        x,
        rng,
    )
    up_k = _full(params["up"]["shard"]["sharded"]["kernel"])  # [2, 12, 8]
    up_b = _full(params["up"]["shard"]["sharded"]["bias"])  # [2, 8]
    down_k = _full(params["down"]["shard"]["sharded"]["kernel"])  # [2, 8, 12]
    down_b = _full(params["down"]["bias"])  # [12]
    w1 = np.concatenate([up_k[i] for i in range(2)], axis=-1)  # [12, 16]
    b1 = np.concatenate([up_b[i] for i in range(2)], axis=-1)  # [16]
    h = np.asarray(jax.nn.gelu(jnp.asarray(np.asarray(x) @ w1 + b1)))
    # row input is the device's hidden shard; full math: h @ [w2_0; w2_1]
    w2 = np.concatenate([down_k[i] for i in range(2)], axis=0)  # [16, 12]
    expected = h @ w2 + down_b
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-4)


def test_row_bias_added_once(mesh_data4_model2, rng):
    """Bias after psum must contribute exactly once, not tp_size times."""
    x = jnp.zeros((2, 8))
    params, out = _run_tp(
        mesh_data4_model2,
        lambda: tp.TPDense(
            features=4,
            style="row",
            split_input=True,
            use_bias=True,
            kernel_init=nn.initializers.zeros,
            bias_init=nn.initializers.ones,
        ),
        x,
        rng,
    )
    # zero weights, zero input -> output == bias exactly; 2.0 would mean the
    # psum double-added it
    np.testing.assert_allclose(np.asarray(out), np.ones((2, 4)), atol=1e-7)


def test_row_init_variance_matches_dense(mesh_data4_model2, rng):
    """Row-parallel kernel init must use global fan-in: shard std == dense std."""
    in_dim, out_dim = 256, 64
    x = jnp.zeros((2, in_dim))
    params, _ = _run_tp(
        mesh_data4_model2,
        lambda: tp.TPDense(features=out_dim, style="row", split_input=True),
        x,
        rng,
    )
    shard_std = float(np.std(_full(params["shard"]["sharded"]["kernel"])))
    dense = nn.Dense(out_dim)
    dense_params = dense.init(jax.random.PRNGKey(0), jnp.zeros((1, in_dim)))
    dense_std = float(np.std(np.asarray(dense_params["params"]["kernel"])))
    assert abs(shard_std - dense_std) / dense_std < 0.15, (
        f"row shard std {shard_std:.4f} vs dense {dense_std:.4f} — init "
        "variance depends on tp degree"
    )


def test_split_over_axis_rejects_indivisible(mesh_data4_model2, rng):
    x = jnp.zeros((2, 9))  # 9 features over tp=2
    with pytest.raises(ValueError, match="silently dropped"):
        jax.eval_shape(
            jax.shard_map(
                lambda x: tp.split_over_axis(x, "model"),
                mesh=mesh_data4_model2,
                in_specs=P(),
                out_specs=P("model"),
                check_vma=False,
            ),
            x,
        )


def test_stack_params_mask_except(mesh_data4_model2):
    """mask_except zeroes the stacked param on all ranks but the chosen one."""

    def body(x):
        params = tp.stack_params({"w": x}, "model", mask_except=1)
        return params["w"].value

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh_data4_model2,
            in_specs=P(),
            out_specs=P("model", None),
            check_vma=False,
        )
    )
    out = np.asarray(f(jnp.ones(3)))  # stacked axis over model: global [2, 3]
    np.testing.assert_allclose(out[0], np.zeros(3))  # rank 0 masked out
    np.testing.assert_allclose(out[1], np.ones(3))  # rank 1 keeps the value


class _TPClassifier(nn.Module):
    hidden: int = 32
    classes: int = 10

    @nn.compact
    def __call__(self, x, train=True):
        h = tp.TPMLP(hidden_features=self.hidden, out_features=32, name="mlp")(x)
        h = nn.silu(h)
        return tp.TPDense(
            features=self.classes + 6, style="column", gather_output=True, name="head"
        )(h).astype(jnp.float32)[..., : self.classes]


def test_tp_training_loss_decreases(mesh_data4_model2, rng):
    """End-to-end: TP model trains under the generic SPMD builder."""
    batch = classification_batch(jax.random.PRNGKey(3), 32, 16, 10)
    model = _TPClassifier()
    init = make_model_init(model, optax.adamw(1e-3), train_arg=True)
    funcs = build_train_functions(
        init,
        make_classification_loss(("data", "model")),
        mesh_data4_model2,
        batch,
        grad_sync_axes=("data", "model"),
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)["loss"]
    for _ in range(10):
        state, m = funcs.step_fn(state, None, batch)
    assert compute(m)["loss"] < first


def test_tp_training_grads_match_dense(mesh_data4_model2, rng):
    """Synced TP gradients == dense gradients on the same logical weights.

    Round-1 regression: per-rank shard_map grads carry a factor of
    ``tp`` for every model-partitioned parameter (the backward sums the
    tp identical replicated-loss cotangents); ``sync_gradients`` must
    divide it back out, while replicated params are fixed by the pmean.
    """
    import flax.linen as nn
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.parallel import fsdp
    from tpu_parallel.parallel.tp import TPDense

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16, name="pre")(x)
            h = TPDense(
                features=32, axis_name="model", style="column",
                use_bias=False, name="up",
            )(x)
            h = nn.gelu(h)
            return TPDense(
                features=16, axis_name="model", style="row",
                use_bias=False, name="down",
            )(h)

    net = Net()
    x = jax.random.normal(rng, (4, 16))

    def init_fn(r, x):
        return net.init({"params": r}, x)["params"]

    probe = jax.shard_map(
        init_fn, mesh=mesh_data4_model2, in_specs=(P(), P()), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(
        jax.eval_shape(probe, jax.random.PRNGKey(0), x)
    )
    params = jax.jit(
        jax.shard_map(
            init_fn, mesh=mesh_data4_model2, in_specs=(P(), P()),
            out_specs=specs, check_vma=False,
        )
    )(jax.random.PRNGKey(0), x)

    def loss_fn(p, x):
        return jnp.mean(net.apply({"params": p}, x) ** 2)

    def synced_grads(p, x):
        g = jax.grad(loss_fn)(p, x)
        return fsdp.sync_gradients(g, ("data", "model"))

    g = jax.jit(
        jax.shard_map(
            synced_grads, mesh=mesh_data4_model2, in_specs=(specs, P()),
            out_specs=specs, check_vma=False,
        )
    )(params, x)

    # dense-equivalent truth from the same logical weights
    up = np.asarray(params["up"]["shard"]["sharded"]["kernel"].value)
    dn = np.asarray(params["down"]["shard"]["sharded"]["kernel"].value)
    W_up = jnp.asarray(np.concatenate([up[0], up[1]], axis=1))
    W_dn = jnp.asarray(np.concatenate([dn[0], dn[1]], axis=0))
    pre_k = jnp.asarray(params["pre"]["kernel"])
    pre_b = jnp.asarray(params["pre"]["bias"])

    def ref_loss(w):
        h = jax.nn.gelu((x @ w["pre_k"] + w["pre_b"]) @ w["up"])
        return jnp.mean((h @ w["down"]) ** 2)

    tg = jax.grad(ref_loss)(
        dict(pre_k=pre_k, pre_b=pre_b, up=W_up, down=W_dn)
    )

    got_pre = np.asarray(g["pre"]["kernel"])
    np.testing.assert_allclose(got_pre, np.asarray(tg["pre_k"]), rtol=1e-4, atol=1e-6)
    got_up = np.concatenate(
        list(np.asarray(g["up"]["shard"]["sharded"]["kernel"].value)), axis=1
    )
    np.testing.assert_allclose(got_up, np.asarray(tg["up"]), rtol=1e-4, atol=1e-6)
    got_dn = np.concatenate(
        list(np.asarray(g["down"]["shard"]["sharded"]["kernel"].value)), axis=0
    )
    np.testing.assert_allclose(got_dn, np.asarray(tg["down"]), rtol=1e-4, atol=1e-6)


def test_vocab_parallel_ce_matches_gathered(mesh_data4_model2, rng):
    """vocab_parallel_cross_entropy on column-sharded logits == plain CE +
    argmax on the gathered logits, for loss AND gradients."""
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.core.losses import vocab_parallel_cross_entropy

    b, s, v = 2, 8, 64
    logits = jax.random.normal(rng, (b, s, v), jnp.float32)
    targets = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0, v)

    def sharded_loss(full_logits):
        def body(full, t):
            # slice this rank's vocab shard, exactly as a column-parallel
            # lm_head would produce it
            shard = tp.split_over_axis(full, "model", axis=-1)
            ce, pred = vocab_parallel_cross_entropy(shard, t, "model")
            return ce, pred

        return jax.shard_map(
            body, mesh=mesh_data4_model2,
            in_specs=(P(), P()), out_specs=(P(), P()),
        )(full_logits, targets)

    ce_tp, pred_tp = jax.jit(sharded_loss)(logits)
    ce_ref = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    np.testing.assert_allclose(
        np.asarray(ce_tp), np.asarray(ce_ref), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(pred_tp), np.asarray(logits.argmax(-1))
    )

    g_tp = jax.jit(jax.grad(lambda l: sharded_loss(l)[0].sum()))(logits)
    g_ref = jax.grad(
        lambda l: optax.softmax_cross_entropy_with_integer_labels(
            l, targets
        ).sum()
    )(logits)
    np.testing.assert_allclose(
        np.asarray(g_tp), np.asarray(g_ref), rtol=1e-5, atol=1e-5
    )
