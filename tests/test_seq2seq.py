"""Encoder-decoder (seq2seq) family: semantics, meshes, generation."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import TrainState, compute
from tpu_parallel.models.seq2seq import (
    EncoderDecoder,
    Seq2SeqBatch,
    make_seq2seq_loss,
    seq2seq_generate,
    tiny_seq2seq,
)
from tpu_parallel.parallel.spmd import build_train_functions


@pytest.fixture(scope="module")
def tiny_model():
    cfg = tiny_seq2seq()
    model = EncoderDecoder(cfg)
    src = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    dst = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 256)
    variables = model.init({"params": jax.random.PRNGKey(0)}, src, dst, train=False)
    return model, variables, src, dst


def test_forward_shapes(tiny_model):
    model, variables, src, dst = tiny_model
    logits = model.apply(variables, src, dst, train=False)
    assert logits.shape == (2, 8, 256)


def test_decoder_is_causal(tiny_model):
    """Perturbing a future decoder token leaves earlier logits unchanged."""
    model, variables, src, dst = tiny_model
    base = model.apply(variables, src, dst, train=False)
    dst2 = dst.at[:, 5].set((dst[:, 5] + 1) % 256)
    pert = model.apply(variables, src, dst2, train=False)
    np.testing.assert_allclose(base[:, :5], pert[:, :5], atol=1e-5)
    assert not np.allclose(base[:, 5:], pert[:, 5:])


def test_every_position_sees_source(tiny_model):
    """Cross-attention: a source perturbation reaches every decoder position
    (bidirectional encoder + full-visibility memory)."""
    model, variables, src, dst = tiny_model
    base = model.apply(variables, src, dst, train=False)
    src2 = src.at[:, 3].set((src[:, 3] + 1) % 256)
    pert = model.apply(variables, src2, dst, train=False)
    diff = np.abs(np.asarray(base) - np.asarray(pert)).max(axis=(0, 2))
    assert (diff > 0).all(), f"some decoder positions blind to source: {diff}"


def test_source_padding_masked(tiny_model):
    """Positions masked by src_mask cannot influence the output — neither
    through encoder self-attention nor through cross-attention."""
    model, variables, src, dst = tiny_model
    mask = jnp.ones((2, 16), bool).at[:, 12:].set(False)
    a = model.apply(variables, src, dst, src_mask=mask, train=False)
    b = model.apply(
        variables, src.at[:, 12:].set(7), dst, src_mask=mask, train=False
    )
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_generate_matches_teacher_forcing(tiny_model):
    """KV-cached decode (self cache + cross cache + position counter) emits
    exactly the greedy path of the full teacher-forced forward."""
    model, variables, src, _ = tiny_model
    toks = seq2seq_generate(
        model, variables["params"], src, max_new_tokens=6, bos_id=1
    )
    forced = jnp.concatenate(
        [jnp.full((2, 1), 1, jnp.int32), toks[:, :-1]], axis=1
    )
    ref = jnp.argmax(
        model.apply(variables, src, forced, train=False).astype(jnp.float32), -1
    )
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))


def test_scan_matches_unrolled():
    """Scanned and unrolled stacks compute the same function on the SAME
    per-layer weights (stacked scan params copied into the per-layer
    scopes, like test_gpt_scan_equals_unrolled)."""
    src = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    dst = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 256)
    cfg_s = tiny_seq2seq(scan_layers=True, remat=False)
    cfg_l = tiny_seq2seq(scan_layers=False, remat=False)
    model_s = EncoderDecoder(cfg_s)
    model_l = EncoderDecoder(cfg_l)
    vars_s = model_s.init({"params": jax.random.PRNGKey(0)}, src, dst, train=False)
    vars_l = model_l.init({"params": jax.random.PRNGKey(0)}, src, dst, train=False)

    rebuilt = jax.tree_util.tree_map(lambda x: x, vars_l["params"])  # copy
    for stack, n in (("encoder", cfg_l.encoder_layers), ("decoder", cfg_l.n_layers)):
        stacked = vars_s["params"][stack]["layers"]["block"]
        for i in range(n):
            rebuilt[stack][f"layer_{i}"] = jax.tree_util.tree_map(
                lambda x: x[i], stacked
            )
    for shared in ("embed", "enc_norm", "dec_norm", "lm_head"):
        rebuilt[shared] = vars_s["params"][shared]

    out_s = model_s.apply(vars_s, src, dst, train=False)
    out_l = model_l.apply({"params": rebuilt}, src, dst, train=False)
    np.testing.assert_allclose(
        np.asarray(out_s), np.asarray(out_l), rtol=1e-4, atol=1e-4
    )


def _s2s_batch(key, batch_size, cfg, length=16):
    """Copy-task batch: target reproduces the source."""
    k1, _ = jax.random.split(key)
    src = jax.random.randint(k1, (batch_size, length), 2, cfg.vocab_size)
    bos = jnp.ones((batch_size, 1), jnp.int32)
    return Seq2SeqBatch(
        src_tokens=src,
        tokens=jnp.concatenate([bos, src[:, :-1]], axis=1)[:, :length],
        targets=src,
        src_mask=jnp.ones_like(src, bool),
    )


def _train(mesh, cfg, steps=8, **build_kwargs):
    batch = _s2s_batch(jax.random.PRNGKey(0), 16, cfg)
    model = EncoderDecoder(cfg)
    tx = optax.adamw(3e-3)

    def init(rng, b):
        variables = model.init(
            {"params": rng}, b.src_tokens, b.tokens, train=False
        )
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"], tx=tx, rng=rng
        )

    funcs = build_train_functions(
        init,
        make_seq2seq_loss(cfg),
        mesh,
        batch,
        batch_spec=P("data"),
        donate=False,
        **build_kwargs,
    )
    state = funcs.init_fn(jax.random.PRNGKey(42), batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)["loss"]
    for _ in range(steps - 1):
        state, m = funcs.step_fn(state, None, batch)
    return first, compute(m)["loss"], state


def test_seq2seq_dp_training(mesh_data8):
    cfg = tiny_seq2seq()
    first, last, _ = _train(mesh_data8, cfg)
    assert last < first


def test_seq2seq_tp_training(mesh_data4_model2):
    """TP trains (vocab-parallel CE path) and shards attention kernels."""
    cfg = tiny_seq2seq()
    first, last, state = _train(
        mesh_data4_model2, cfg, grad_sync_axes=("data", "model")
    )
    assert last < first
    specs = nn.get_partition_spec(state).params
    flat = jax.tree_util.tree_leaves_with_path(specs)
    assert any("model" in str(s) for _, s in flat), "no model-sharded params"


def test_seq2seq_fsdp_training(mesh_data8):
    """FSDP shards encoder, decoder (incl. cross-attention), and lm_head."""
    cfg = tiny_seq2seq(fsdp=True, fsdp_min_size=0)
    first, last, state = _train(mesh_data8, cfg)
    assert last < first
    specs = nn.get_partition_spec(state).params
    flat = jax.tree_util.tree_leaves_with_path(specs)
    for sub in ("encoder", "cross_attn", "lm_head"):
        hits = [
            s
            for p, s in flat
            if sub in jax.tree_util.keystr(p)
            and "kernel" in jax.tree_util.keystr(p)
        ]
        assert hits and all("data" in str(s) for s in hits), (sub, hits)


def test_seq2seq_vocab_parallel_ce_matches_full(mesh_data4_model2):
    """Under TP, the loss path's vocab-parallel CE (column-sharded logits,
    psum'd softmax statistics) equals plain CE on the gathered full-vocab
    logits — same params, same mesh, same tokens."""
    from jax.sharding import PartitionSpec

    from tpu_parallel.core.losses import token_cross_entropy
    from tpu_parallel.models.gpt import _lm_head_params, make_ce_fn

    cfg = tiny_seq2seq()
    model = EncoderDecoder(cfg)
    batch = _s2s_batch(jax.random.PRNGKey(0), 4, cfg)
    ce_fn = make_ce_fn(cfg)

    def init_fn(rng, b):
        return model.init(
            {"params": rng}, b.src_tokens, b.tokens, train=False
        )["params"]

    P_ = PartitionSpec
    probe = jax.shard_map(
        init_fn, mesh=mesh_data4_model2, in_specs=(P_(), P_()),
        out_specs=P_(), check_vma=False,
    )
    specs = nn.get_partition_spec(
        jax.eval_shape(probe, jax.random.PRNGKey(0), batch)
    )
    params = jax.jit(
        jax.shard_map(
            init_fn, mesh=mesh_data4_model2, in_specs=(P_(), P_()),
            out_specs=specs, check_vma=False,
        )
    )(jax.random.PRNGKey(0), batch)

    def both(params, b):
        mask = jnp.ones(b.targets.shape, jnp.float32)
        hidden = model.apply(
            {"params": params}, b.src_tokens, b.tokens,
            src_mask=b.src_mask, train=False, hidden_only=True,
        )
        vp_sum, _ = ce_fn(_lm_head_params(cfg, params), hidden, b.targets, mask)
        logits = model.apply(
            {"params": params}, b.src_tokens, b.tokens,
            src_mask=b.src_mask, train=False,
        )
        full_sum = (token_cross_entropy(logits, b.targets) * mask).sum()
        return vp_sum, full_sum

    vp, full = jax.jit(
        jax.shard_map(
            both, mesh=mesh_data4_model2, in_specs=(specs, P_()),
            out_specs=P_(), check_vma=False,
        )
    )(params, batch)
    np.testing.assert_allclose(np.asarray(vp), np.asarray(full), rtol=1e-5)


def test_sharded_generate_matches_exported(mesh_data8):
    """Data-mesh sharded decoding == plain generate on the exported params
    (same trained weights through both serving paths)."""
    from tpu_parallel.models.seq2seq import seq2seq_generate_sharded
    from tpu_parallel.parallel.tp import export_single_device_params

    cfg = tiny_seq2seq()
    _, _, state = _train(mesh_data8, cfg, steps=4)
    src = jax.random.randint(jax.random.PRNGKey(7), (8, 16), 2, cfg.vocab_size)
    model = EncoderDecoder(cfg)
    sharded = seq2seq_generate_sharded(
        model, state.params, src, mesh_data8, max_new_tokens=5, bos_id=1
    )
    plain = seq2seq_generate(
        model,
        export_single_device_params(state.params),
        src,
        max_new_tokens=5,
        bos_id=1,
    )
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(plain))


def test_sharded_generate_tp_mesh(mesh_data4_model2):
    """TP-split weights serve without export, and the greedy tokens equal
    the teacher-forced argmax of the SAME TP state's full forward — a
    known-good reference for the vocab-parallel sampling path (a broken
    shard offset would emit deterministic-but-wrong tokens)."""
    from jax.sharding import PartitionSpec

    from tpu_parallel.models.seq2seq import seq2seq_generate_sharded

    cfg = tiny_seq2seq()
    _, _, state = _train(
        mesh_data4_model2, cfg, steps=2, grad_sync_axes=("data", "model")
    )
    src = jax.random.randint(jax.random.PRNGKey(7), (4, 16), 2, cfg.vocab_size)
    model = EncoderDecoder(cfg)
    toks = seq2seq_generate_sharded(
        model, state.params, src, mesh_data4_model2, max_new_tokens=5, bos_id=1
    )
    assert toks.shape == (4, 5)

    forced = jnp.concatenate(
        [jnp.full((4, 1), 1, jnp.int32), toks[:, :-1]], axis=1
    )
    P_ = PartitionSpec
    specs = nn.get_partition_spec(state.params)

    def fwd(params, s, d):
        # full forward under the mesh; gathered lm_head logits -> argmax
        return jnp.argmax(
            model.apply({"params": params}, s, d, train=False).astype(
                jnp.float32
            ),
            -1,
        )

    ref = jax.jit(
        jax.shard_map(
            fwd,
            mesh=mesh_data4_model2,
            in_specs=(specs, P_("data"), P_("data")),
            out_specs=P_("data"),
            check_vma=False,
        )
    )(state.params, src, forced)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_seq2seq_sp_training(impl):
    """Both stacks shard the token axis; cross-attention gathers the
    projected source K/V so sharded decoder queries see the whole source.
    Loss decreases end-to-end on a (data, seq) mesh."""
    from tpu_parallel.runtime import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=2, seq=4))
    cfg = tiny_seq2seq(attn_impl=impl, seq_len=64, src_seq_len=64)
    batch = _s2s_batch(jax.random.PRNGKey(0), 8, cfg, length=64)
    model = EncoderDecoder(cfg)
    tx = optax.adamw(3e-3)

    def init(rng_, b):
        v = model.init({"params": rng_}, b.src_tokens, b.tokens, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=rng_
        )

    from tpu_parallel.parallel.spmd import build_train_functions as btf

    funcs = btf(
        init, make_seq2seq_loss(cfg), mesh, batch,
        batch_spec=P("data", "seq"),
        grad_sync_axes=("data", "seq"), metric_axes=("data", "seq"),
        donate=False,
        # flash kernels run interpret-mode on CPU: JAX vma limitation
        check_vma=False,
    )
    state = funcs.init_fn(jax.random.PRNGKey(42), batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)["loss"]
    for _ in range(5):
        state, m = funcs.step_fn(state, None, batch)
    assert compute(m)["loss"] < first


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_seq2seq_sp_matches_dense(impl):
    """The SP forward computes the SAME function: on one mesh, the SP
    model's global-mean loss over the seq-SHARDED batch equals the xla
    model's over the seq-REPLICATED batch — identical params (the mesh
    layout is shared; only the attention impl and batch sharding differ)."""
    from jax import lax
    from jax.sharding import PartitionSpec
    from tpu_parallel.runtime import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=2, seq=4))
    cfg_r = tiny_seq2seq(attn_impl=impl, seq_len=64, src_seq_len=64)
    cfg_d = tiny_seq2seq(attn_impl="xla", seq_len=64, src_seq_len=64)
    batch = _s2s_batch(jax.random.PRNGKey(0), 2, cfg_r, length=64)
    model_r = EncoderDecoder(cfg_r)
    model_d = EncoderDecoder(cfg_d)
    P_ = PartitionSpec

    def init_fn(rng, b):
        return model_d.init(
            {"params": rng}, b.src_tokens, b.tokens, train=False
        )["params"]

    probe = jax.shard_map(
        init_fn, mesh=mesh, in_specs=(P_(), P_()), out_specs=P_(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(
        jax.eval_shape(probe, jax.random.PRNGKey(0), batch)
    )
    params = jax.jit(
        jax.shard_map(
            init_fn, mesh=mesh, in_specs=(P_(), P_()), out_specs=specs,
            check_vma=False,
        )
    )(jax.random.PRNGKey(0), batch)

    def mean_loss(loss_fn, apply_fn):
        def f(params, b):
            _, m = loss_fn(params, apply_fn, b, jax.random.PRNGKey(1))
            su, n = m["loss"]
            axes = ("data", "seq")
            return lax.psum(su, axes) / lax.psum(n, axes)

        return f

    sp = jax.jit(
        jax.shard_map(
            mean_loss(make_seq2seq_loss(cfg_r, train=False), model_r.apply),
            mesh=mesh, in_specs=(specs, P_("data", "seq")), out_specs=P_(),
            check_vma=False,
        )
    )(params, batch)
    dense = jax.jit(
        jax.shard_map(
            mean_loss(make_seq2seq_loss(cfg_d, train=False), model_d.apply),
            mesh=mesh, in_specs=(specs, P_("data", None)), out_specs=P_(),
            check_vma=False,
        )
    )(params, batch)
    np.testing.assert_allclose(float(sp), float(dense), rtol=1e-4)


def test_seq2seq_moe_training(mesh_data4_model2):
    """Switch-style MoE encoder-decoder: routed experts replace the MLP in
    BOTH stacks, expert-parallel over the model axis, balance aux loss
    collected across encoder+decoder blocks.  (The original Switch
    Transformer is exactly a T5-shaped MoE.)"""
    cfg = tiny_seq2seq(moe_experts=4, moe_top_k=1)
    batch = _s2s_batch(jax.random.PRNGKey(0), 16, cfg)
    model = EncoderDecoder(cfg)
    tx = optax.adamw(3e-3)

    def init(rng, b):
        v = model.init({"params": rng}, b.src_tokens, b.tokens, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=rng
        )

    funcs = build_train_functions(
        init, make_seq2seq_loss(cfg), mesh_data4_model2, batch,
        batch_spec=P("data"), grad_sync_axes=("data", "model"), donate=False,
    )
    state = funcs.init_fn(jax.random.PRNGKey(42), batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)
    assert "moe_balance" in first and first["moe_balance"] > 0
    for _ in range(7):
        state, m = funcs.step_fn(state, None, batch)
    assert compute(m)["loss"] < first["loss"]


def test_seq2seq_pp_training(mesh_pipe4_data2):
    """Encoder-decoder pipeline: each pipe rank owns enc AND dec chunks,
    two sequential GPipe passes, memory broadcast between them, loss
    masked to the last rank.  Loss decreases end-to-end."""
    cfg = tiny_seq2seq(pipe_size=4, enc_layers=4, n_layers=4, num_microbatches=4)
    first, last, state = _train(
        mesh_pipe4_data2,
        cfg,
        grad_sync_axes=("data",),
        grad_psum_axes=("pipe",),
        metric_axes=("data", "pipe"),
    )
    assert last < first
    # stage params are per-rank: pipe must appear in the sharding
    specs = nn.get_partition_spec(state).params
    flat = jax.tree_util.tree_leaves_with_path(specs)
    assert any("pipe" in str(spec) for _, spec in flat), "no pipe-sharded params"


def test_loss_runs_without_mesh():
    """The loss (like the model) degrades gracefully to plain jit: axis
    folds skip unbound axes instead of dying in axis_index — single-chip
    training needs no ceremonial 1-device mesh."""
    cfg = tiny_seq2seq()
    model = EncoderDecoder(cfg)
    batch = _s2s_batch(jax.random.PRNGKey(0), 4, cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, batch.src_tokens, batch.tokens,
        train=False,
    )
    loss_fn = make_seq2seq_loss(cfg)
    loss, metrics = jax.jit(
        lambda p, b: loss_fn(p, model.apply, b, jax.random.PRNGKey(1))
    )(variables["params"], batch)
    assert np.isfinite(float(loss))


def test_eval_forward_needs_no_dropout_rng():
    """train=False must deactivate every dropout (incl. cross-attention's):
    a bare apply without a 'dropout' rng is the eval contract."""
    cfg = tiny_seq2seq(dropout_rate=0.1)
    model = EncoderDecoder(cfg)
    src = jnp.zeros((1, 8), jnp.int32)
    dst = jnp.zeros((1, 4), jnp.int32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        src, dst, train=True,
    )
    a = model.apply(variables, src, dst, train=False)
    b = model.apply(variables, src, dst, train=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_refusals_are_loud():
    src = jnp.zeros((1, 8), jnp.int32)
    dst = jnp.zeros((1, 8), jnp.int32)
    # (ring/ulysses and pipe_size>1 no longer refuse: SP and PP compose —
    # see test_seq2seq_sp_training / test_seq2seq_pp_training)
    # (moe alone no longer refuses: Switch-style MoE composes — see
    # test_seq2seq_moe_training; the PP combo still does)
    for bad in (
        dict(moe_experts=2, pipe_size=2),
        dict(prenorm=False),
        dict(embed_norm=True),
        dict(pipe_size=2, pipe_interleave=2),
    ):
        with pytest.raises(NotImplementedError):
            EncoderDecoder(tiny_seq2seq(**bad)).init(
                {"params": jax.random.PRNGKey(0)}, src, dst, train=False
            )
    # interleave without a pipe degree: silently-ignored knob refused
    with pytest.raises(ValueError, match="pipe_interleave"):
        EncoderDecoder(tiny_seq2seq(pipe_interleave=2)).init(
            {"params": jax.random.PRNGKey(0)}, src, dst, train=False
        )


def test_mesh_bound_refusals_are_loud(mesh_pipe4_data2):
    """The refusals that only fire under a bound mesh axis: relative bias
    under PP (init-time) and incremental decoding under a pipe mesh
    (apply-time) raise instead of silently corrupting."""
    from jax.sharding import PartitionSpec

    P_ = PartitionSpec
    src = jnp.zeros((8, 8), jnp.int32)
    dst = jnp.zeros((8, 8), jnp.int32)

    # relative bias + PP: setup refuses during the mesh init trace
    cfg_rel = tiny_seq2seq(
        pipe_size=4, enc_layers=4, n_layers=4, positional="relative",
        norm="rmsnorm",
    )
    model_rel = EncoderDecoder(cfg_rel)
    with pytest.raises(NotImplementedError, match="relative"):
        jax.eval_shape(
            jax.shard_map(
                lambda s, d: model_rel.init(
                    {"params": jax.random.PRNGKey(0)}, s, d, train=False
                ),
                mesh=mesh_pipe4_data2,
                in_specs=(P_("data"), P_("data")),
                out_specs=P_(),
                check_vma=False,
            ),
            src, dst,
        )

    # decoding on a pipe mesh: decode() refuses at trace time
    cfg_pp = tiny_seq2seq(pipe_size=4, enc_layers=4, n_layers=4)
    model_pp = EncoderDecoder(cfg_pp)

    def try_decode(s, d):
        v = model_pp.init({"params": jax.random.PRNGKey(0)}, s, d, train=False)
        return model_pp.apply(
            v, s, d, train=False, decode=True, mutable=["cache"]
        )

    with pytest.raises(NotImplementedError, match="decoding"):
        jax.eval_shape(
            jax.shard_map(
                try_decode, mesh=mesh_pipe4_data2,
                in_specs=(P_("data"), P_("data")), out_specs=P_(),
                check_vma=False,
            ),
            src, dst,
        )


def test_sp_decode_refusal():
    """Decoding with a bound seq axis refuses (the serving batch is
    seq-replicated; SP offsets would silently corrupt it)."""
    from jax.sharding import PartitionSpec
    from tpu_parallel.runtime import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=2, seq=4))
    cfg = tiny_seq2seq(attn_impl="ring", seq_len=64, src_seq_len=64)
    model = EncoderDecoder(cfg)
    P_ = PartitionSpec
    src = jnp.zeros((8, 64), jnp.int32)
    dst = jnp.zeros((8, 64), jnp.int32)

    def try_decode(s, d):
        v = model.init({"params": jax.random.PRNGKey(0)}, s, d, train=False)
        return model.apply(
            v, s, d, train=False, decode=True, mutable=["cache"]
        )

    with pytest.raises(NotImplementedError, match="sequence parallelism"):
        jax.eval_shape(
            jax.shard_map(
                try_decode, mesh=mesh,
                in_specs=(P_("data", "seq"), P_("data", "seq")),
                out_specs=P_(), check_vma=False,
            ),
            src, dst,
        )


def test_beam_search_beats_or_matches_greedy(tiny_model):
    """Beam=1 equals greedy exactly; beam=4's sequence log-probability is
    scored exactly (the returned score equals the teacher-forced
    log-probability of the returned tokens — pinning the per-step cache
    reorder that routes each beam to its own self K/V rows)."""
    from tpu_parallel.models.seq2seq import seq2seq_generate_beam

    model, variables, src, _ = tiny_model
    params = variables["params"]
    greedy = seq2seq_generate(
        model, params, src, max_new_tokens=6, bos_id=1
    )
    beam1, s1 = seq2seq_generate_beam(
        model, params, src, bos_id=1, max_new_tokens=6, num_beams=1
    )
    np.testing.assert_array_equal(np.asarray(beam1), np.asarray(greedy))

    def seq_logp(tokens):
        forced = jnp.concatenate(
            [jnp.full((tokens.shape[0], 1), 1, jnp.int32), tokens[:, :-1]], 1
        )
        logits = model.apply(variables, src, forced, train=False).astype(
            jnp.float32
        )
        lp = jax.nn.log_softmax(logits)
        return jnp.take_along_axis(lp, tokens[..., None], -1)[..., 0].sum(-1)

    beam4, s4 = seq2seq_generate_beam(
        model, params, src, bos_id=1, max_new_tokens=6, num_beams=4
    )
    np.testing.assert_allclose(
        np.asarray(s1), np.asarray(seq_logp(beam1)), rtol=1e-4, atol=1e-4
    )
    # the returned score must be the TRUE teacher-forced log-probability of
    # the returned beam-4 tokens — this pins the non-trivial cache reorder
    # (a row routed to the wrong beam's K/V would break the equality)
    np.testing.assert_allclose(
        np.asarray(s4), np.asarray(seq_logp(beam4)), rtol=1e-4, atol=1e-4
    )


def test_seq2seq_beam_lazy_matches_eager(tiny_model):
    """Lazy beam decode (ancestry tables, no per-step self-cache gather) is
    token- and score-exact against the eager reorder for the
    encoder-decoder family (cross caches are beam-invariant in both)."""
    from tpu_parallel.models.seq2seq import seq2seq_generate_beam

    model, variables, src, _ = tiny_model
    params = variables["params"]
    lazy_toks, lazy_s = seq2seq_generate_beam(
        model, params, src, bos_id=1, max_new_tokens=7, num_beams=4, lazy=True
    )
    eager_toks, eager_s = seq2seq_generate_beam(
        model, params, src, bos_id=1, max_new_tokens=7, num_beams=4, lazy=False
    )
    np.testing.assert_array_equal(np.asarray(lazy_toks), np.asarray(eager_toks))
    np.testing.assert_allclose(
        np.asarray(lazy_s), np.asarray(eager_s), rtol=1e-5, atol=1e-5
    )
