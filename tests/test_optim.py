"""Sharding-aware optimizer transform tests."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core.optim import clip_by_global_norm_sharded, global_norm_sharded


def test_global_norm_counts_all_shards(mesh_data8):
    """Norm of a data-partitioned grad must include every rank's shard."""

    def body():
        idx = jax.lax.axis_index("data").astype(jnp.float32)
        grads = {
            "sharded": nn.Partitioned(jnp.full((2,), idx), names=("data",)),
            "replicated": jnp.ones((3,)),
        }
        return global_norm_sharded(grads)[None]

    f = jax.jit(
        jax.shard_map(body, mesh=mesh_data8, in_specs=(), out_specs=P("data"),
                      check_vma=False)
    )
    norms = np.asarray(f())
    # expected: sqrt(sum_i 2*i^2 + 3) = sqrt(2*140 + 3)
    expected = np.sqrt(2 * sum(i * i for i in range(8)) + 3.0)
    np.testing.assert_allclose(norms, np.full(8, expected), rtol=1e-6)


def test_clip_factor_identical_across_ranks(mesh_data8):
    """Every rank must scale by the same factor (stock optax clip does not)."""

    def body():
        idx = jax.lax.axis_index("data").astype(jnp.float32)
        grads = {
            "sharded": nn.Partitioned(jnp.full((4,), idx + 1.0), names=("data",)),
            "replicated": jnp.full((4,), 2.0),
        }
        clip = clip_by_global_norm_sharded(1.0)
        state = clip.init(None)
        clipped, _ = clip.update(grads, state)
        # replicated leaf after clipping must be identical everywhere
        return clipped["replicated"][None]

    f = jax.jit(
        jax.shard_map(body, mesh=mesh_data8, in_specs=(), out_specs=P("data"),
                      check_vma=False)
    )
    per_rank = np.asarray(f())
    for r in range(1, 8):
        np.testing.assert_array_equal(per_rank[r], per_rank[0])
    # and the clip actually clipped (norm >> 1)
    assert np.all(np.abs(per_rank) < 2.0)


@pytest.mark.parametrize("name", ["lion", "sgd"])
def test_optimizer_families_train(mesh_data8, name):
    """Every optimizer family wires through the sharded train step and
    decreases loss (adamw is every other test's default)."""
    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig

    config = TrainerConfig(
        model="tiny",
        optimizer=name,
        mesh=MeshConfig(data=-1),
        global_batch_size=16,
        steps=6,
        learning_rate=1e-3 if name == "lion" else 1e-2,
        log_every=6,
        donate=False,
    )
    trainer = Trainer(config)
    trainer.init()
    state, m = trainer.state, None
    state, m0 = trainer.funcs.step_fn(state, None, trainer.example_batch)
    from tpu_parallel.core import compute

    first = compute(m0)["loss"]
    for _ in range(5):
        state, m = trainer.funcs.step_fn(state, None, trainer.example_batch)
    assert compute(m)["loss"] < first, name


def test_unknown_optimizer_rejected():
    from tpu_parallel.train_lib import TrainerConfig, make_optimizer

    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(TrainerConfig(optimizer="adamw2"))
    # adafactor is explicitly unsupported (FactoredState breaks Partitioned
    # spec discovery) — must fail loudly, not at trace time
    with pytest.raises(ValueError, match="adafactor"):
        make_optimizer(TrainerConfig(optimizer="adafactor"))


@pytest.mark.fast
@pytest.mark.parametrize("name", ["cosine", "linear", "constant"])
def test_lr_schedules(name):
    """Each schedule warms up linearly, then follows its decay shape."""
    from tpu_parallel.train_lib import TrainerConfig, make_optimizer

    from tpu_parallel.train_lib import make_lr_schedule

    cfg = TrainerConfig(lr_schedule=name, learning_rate=1e-3, warmup_steps=10, steps=100)
    make_optimizer(cfg)  # must construct
    sched = make_lr_schedule(cfg)
    assert float(sched(0)) == 0.0
    assert abs(float(sched(10)) - 1e-3) < 1e-9
    if name == "constant":
        assert abs(float(sched(99)) - 1e-3) < 1e-9
    else:
        assert float(sched(99)) < 1e-3 / 2


def test_unknown_lr_schedule_rejected():
    from tpu_parallel.train_lib import TrainerConfig, make_optimizer

    with pytest.raises(ValueError, match="unknown lr_schedule"):
        make_optimizer(TrainerConfig(lr_schedule="cyclical"))
