"""Request-lifecycle span tracer.

Records WHAT happened WHEN as named spans on named tracks: the serving
engine opens one track per cache slot plus a ``scheduler`` track, the
trainer a ``trainer`` track, and :mod:`tpu_parallel.obs.exporters` lays
the spans out as a Chrome trace-event file Perfetto opens directly — one
request's life reads left to right as
``queue -> prefill[chunk i] -> decode/verify... -> finish``.

Two span shapes:

- **Complete spans** (the default): a ``[start, end]`` interval on one
  track.  Spans on a track must be sequential or properly nested (the
  Chrome ``X`` event contract); everything the engine emits per tick is.
- **Async spans** (``start_async``): intervals that legitimately overlap
  others on their track — queue-wait spans of concurrently queued
  requests.  Exported as Chrome ``b``/``e`` nestable-async pairs, which
  Perfetto renders on per-id sub-rows instead of corrupting the track.

Since the fleet-tracing PR each span also carries an IDENTITY —
``span_id`` / ``parent_id`` / ``trace_id`` — so spans emitted by
different PROCESSES (router, prefill daemon, decode daemon) can be
stitched back into one tree per request.  The wire carries a
:class:`TraceContext` (trace id + the parent span id for anything the
receiver emits) in the ``X-TP-Trace`` header; inside a process the
tracer stamps it onto spans by request id via :meth:`Tracer.bind_trace`
— the engine and frontend already attribute every span/instant with
``request_id=`` (or the router's ``rid=``), so they need no API change
to participate.

Timestamps come from an injectable monotonic ``clock`` so lifecycle tests
run on a fake clock, deterministically.

**Disabled tracing is near-zero cost**: the module-level :data:`NULL_TRACER`
(the engine/trainer default) returns one shared no-op span from every
call — no timestamp read, no allocation, no list append.  Hot loops that
would even BUILD attribute dicts per token guard on ``tracer.enabled``.
The trace-binding surface keeps that contract: ``bind_trace`` /
``release_trace`` on the null tracer are no-ops, and an enabled tracer
with ZERO bindings pays one falsy dict check per span.
"""

from __future__ import annotations

import itertools
import time
import uuid
from typing import Callable, Dict, List, Optional

TRACE_HEADER = "X-TP-Trace"

_TRACE_ID_LEN = 32  # 128-bit trace id, lowercase hex
_SPAN_ID_LEN = 16  # 64-bit span id, lowercase hex
_HEX = set("0123456789abcdef")


class TraceContext:
    """The portable identity of one request's trace: a 128-bit trace id
    plus the span id every span the HOLDER emits should parent to.

    Crossing a process boundary, :meth:`fork` mints a child context (same
    trace, fresh parent span id) whose id the SENDER assigns to its wire
    span — so the receiver's spans hang off the wire crossing, and the
    stitched tree keeps its depth.  On the wire it travels as the
    ``X-TP-Trace`` header, ``<trace32hex>-<span16hex>``.
    """

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(uuid.uuid4().hex, uuid.uuid4().hex[:_SPAN_ID_LEN])

    def fork(self) -> "TraceContext":
        """Same trace, fresh parent span id (a child boundary)."""
        return TraceContext(
            self.trace_id, uuid.uuid4().hex[:_SPAN_ID_LEN]
        )

    def header_value(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    @classmethod
    def parse(cls, value: Optional[str]) -> Optional["TraceContext"]:
        """The inbound-header gate: a well-formed ``<trace>-<span>``
        pair or None — garbage from a client never becomes identity."""
        if not value or not isinstance(value, str):
            return None
        trace_id, sep, span_id = value.strip().partition("-")
        if not sep:
            return None
        if len(trace_id) != _TRACE_ID_LEN or len(span_id) != _SPAN_ID_LEN:
            return None
        if not (_HEX >= set(trace_id) and _HEX >= set(span_id)):
            return None
        return cls(trace_id, span_id)

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


class Span:
    """One named interval on a track.  Usable as a context manager for
    lexically-scoped work, or held across ticks and closed with
    :meth:`finish` (the engine's queue-wait spans live for many ticks)."""

    __slots__ = ("name", "track", "start", "end", "attrs", "async_id",
                 "span_id", "parent_id", "trace_id", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, track: str,
                 attrs: Dict[str, object], start: float,
                 async_id: Optional[str] = None):
        self._tracer = tracer
        self.name = name
        self.track = track
        self.attrs = attrs
        self.start = start
        self.end: Optional[float] = None
        self.async_id = async_id
        self.span_id: Optional[str] = None
        self.parent_id: Optional[str] = None
        self.trace_id: Optional[str] = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def finish(self, **attrs) -> "Span":
        if attrs:
            self.attrs.update(attrs)
        if self.end is None:
            self.end = self._tracer.now()
        return self

    def to_dict(self) -> Dict[str, object]:
        """The span-log record body (see :mod:`tpu_parallel.obs.spool`)."""
        rec: Dict[str, object] = {
            "name": self.name,
            "track": self.track,
            "start": self.start,
            "end": self.end,
            "attrs": dict(self.attrs),
        }
        if self.async_id is not None:
            rec["async_id"] = self.async_id
        if self.trace_id is not None:
            rec["trace_id"] = self.trace_id
            rec["span_id"] = self.span_id
            rec["parent_id"] = self.parent_id
        return rec

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class _NullSpan:
    """The shared do-nothing span: every NullTracer call returns THIS
    object, so a disabled tracer allocates nothing per call."""

    __slots__ = ()

    def set(self, **attrs) -> "_NullSpan":
        return self

    def finish(self, **attrs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Append-only span/instant recorder.

    ``span``/``start`` open a complete span (``span`` reads better under
    ``with``; they are the same call), ``start_async`` an overlap-safe
    async span, ``record`` retro-records an interval measured by the
    caller (the engine's batched prefill fans one device call out into
    per-slot spans sharing the measured window), ``instant`` drops a
    zero-duration marker.

    **Trace binding**: ``bind_trace(request_id, ctx)`` makes every
    subsequent span/instant whose attrs carry that ``request_id`` (or
    ``rid``) a child of ``ctx`` — stamped with the trace id, a fresh
    span id, and ``ctx.span_id`` as parent — until ``release_trace``.
    The lookup costs one falsy dict check when nothing is bound.
    """

    enabled = True

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.spans: List[Span] = []
        self.instants: List[Dict] = []
        self._bindings: Dict[str, TraceContext] = {}
        # per-tracer span-id mint: a process nonce + a counter keeps ids
        # unique across the fleet without a uuid4 per span
        self._span_nonce = uuid.uuid4().hex[:8]
        self._span_seq = itertools.count()

    def now(self) -> float:
        return self.clock()

    def next_span_id(self) -> str:
        return f"{self._span_nonce}{next(self._span_seq):08x}"

    # -- trace binding ----------------------------------------------------

    def bind_trace(self, request_id: str, ctx: TraceContext) -> None:
        self._bindings[request_id] = ctx

    def release_trace(self, request_id: str) -> None:
        self._bindings.pop(request_id, None)

    def trace_of(self, request_id: str) -> Optional[TraceContext]:
        return self._bindings.get(request_id)

    def _stamp(self, span: Span) -> Span:
        if self._bindings:
            key = span.attrs.get("request_id") or span.attrs.get("rid")
            ctx = self._bindings.get(key) if key is not None else None
            if ctx is not None:
                span.trace_id = ctx.trace_id
                span.parent_id = ctx.span_id
                span.span_id = self.next_span_id()
        return span

    # -- recording --------------------------------------------------------

    def start(self, name: str, track: str = "main", **attrs) -> Span:
        span = Span(self, name, track, attrs, self.clock())
        self._stamp(span)
        self.spans.append(span)
        return span

    span = start

    def start_async(self, name: str, track: str, async_id: str,
                    **attrs) -> Span:
        span = Span(self, name, track, attrs, self.clock(),
                    async_id=async_id)
        self._stamp(span)
        self.spans.append(span)
        return span

    def record(self, name: str, track: str, start: float, end: float,
               **attrs) -> Span:
        span = Span(self, name, track, attrs, start)
        span.end = end
        self._stamp(span)
        self.spans.append(span)
        return span

    def instant(self, name: str, track: str = "main", **attrs) -> None:
        ev = {"name": name, "track": track, "ts": self.clock(),
              "attrs": attrs}
        if self._bindings:
            key = attrs.get("request_id") or attrs.get("rid")
            ctx = self._bindings.get(key) if key is not None else None
            if ctx is not None:
                ev["trace_id"] = ctx.trace_id
                ev["parent_id"] = ctx.span_id
        self.instants.append(ev)

    def tracks(self) -> List[str]:
        """Every track touched, ``scheduler`` and ``trainer`` first, the
        rest natural-sorted (``slot 2`` before ``slot 10``) — the
        exporter's row order."""
        seen = {s.track for s in self.spans}
        seen.update(ev["track"] for ev in self.instants)
        head = [t for t in ("scheduler", "trainer") if t in seen]

        def natural(track: str):
            prefix, _, tail = track.rpartition(" ")
            if tail.isdigit():
                return (prefix, int(tail))
            return (track, -1)

        return head + sorted(seen - set(head), key=natural)


class NullTracer:
    """The disabled tracer: same surface as :class:`Tracer`, no clock
    reads, no storage.  ``enabled`` is False so hot loops can skip even
    building the attribute dicts."""

    enabled = False

    def now(self) -> float:
        return 0.0

    def next_span_id(self) -> str:
        return ""

    def bind_trace(self, request_id: str, ctx: TraceContext) -> None:
        pass

    def release_trace(self, request_id: str) -> None:
        pass

    def trace_of(self, request_id: str) -> None:
        return None

    def start(self, name: str, track: str = "main", **attrs) -> _NullSpan:
        return NULL_SPAN

    span = start

    def start_async(self, name: str, track: str, async_id: str,
                    **attrs) -> _NullSpan:
        return NULL_SPAN

    def record(self, name: str, track: str, start: float, end: float,
               **attrs) -> _NullSpan:
        return NULL_SPAN

    def instant(self, name: str, track: str = "main", **attrs) -> None:
        pass

    def tracks(self) -> List[str]:
        return []

    @property
    def spans(self) -> List[Span]:
        return []

    @property
    def instants(self) -> List[Dict]:
        return []


NULL_TRACER = NullTracer()
