"""Token-stream data loading: memmap datasets + multi-host global batches.

No reference capability exists (the reference trains on inline synthetic
tensors — SURVEY.md §1 "no data-loading layer"); this supplies the input
pipeline a real framework needs, TPU-first:

- :class:`TokenDataset` reads a flat binary token file through ``np.memmap``
  (zero-copy, no RAM blowup at corpus scale) and cuts deterministic,
  seeded, shuffled ``seq_len+1`` windows — the standard GPT-style layout
  (same format as nanoGPT/llm.jax ``.bin`` corpora).
- :func:`make_global_batch` turns each process's **local** shard of a batch
  into one logically-global sharded ``jax.Array`` via
  ``jax.make_array_from_process_local_data`` — the multi-host feeding
  pattern (each host reads only its slice; XLA sees a single global array
  laid out over the mesh's data axis).
- :class:`DataLoader` composes the two into the iterator the Trainer
  consumes, with per-process disjoint sampling derived from
  ``jax.process_index()``.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpu_parallel.core.state import TextBatch


class TokenDataset:
    """Windows over flat token streams (memmap file(s) or in-memory array).

    ``tokens`` may be one path / array, or a **list** of paths/arrays — a
    sharded corpus.  Windows never cross shard boundaries (each shard
    contributes ``(len - 1) // seq_len`` windows); shards stay memmapped,
    so corpus size never hits RAM.

    ``sample(epoch_rng, index)`` is deterministic: the same seed and index
    always give the same window, so a resumed run (checkpointed step count)
    replays the identical data order.
    """

    def __init__(self, tokens, seq_len: int):
        if not isinstance(tokens, (list, tuple)):
            tokens = [tokens]
        self.shards = [
            np.memmap(t, dtype=np.uint16, mode="r") if isinstance(t, str) else t
            for t in tokens
        ]
        self.seq_len = seq_len
        per_shard = [max(0, (len(s) - 1) // seq_len) for s in self.shards]
        # cumulative window counts: window i lives in the shard whose
        # cumulative range contains i
        self._cum = np.cumsum([0] + per_shard)
        self.num_windows = int(self._cum[-1])
        if self.num_windows <= 0:
            raise ValueError(
                f"streams of {[len(s) for s in self.shards]} tokens too "
                f"short for seq_len={seq_len}"
            )

    @staticmethod
    def write_bin(path: str, tokens: np.ndarray) -> None:
        """Write a token array in the flat uint16 format ``__init__`` reads."""
        np.asarray(tokens, dtype=np.uint16).tofile(path)

    def window(self, i: int) -> np.ndarray:
        """Window ``i``: ``seq_len + 1`` tokens (inputs + shifted targets)."""
        shard = int(np.searchsorted(self._cum, i, side="right")) - 1
        start = (i - int(self._cum[shard])) * self.seq_len
        return np.asarray(
            self.shards[shard][start : start + self.seq_len + 1], np.int32
        )

    def batch(self, order: np.ndarray) -> TextBatch:
        """Assemble the windows in ``order`` into a TextBatch (numpy)."""
        rows = np.stack([self.window(int(i)) for i in order])
        seq = self.seq_len
        return TextBatch(
            tokens=rows[:, :-1],
            targets=rows[:, 1:],
            loss_mask=np.ones((len(order), seq), np.float32),
            positions=np.broadcast_to(np.arange(seq), (len(order), seq)),
        )


def make_global_batch(
    local_batch: TextBatch, mesh: Mesh, batch_spec: P = P("data")
) -> TextBatch:
    """Lift per-process local arrays into one global sharded TextBatch.

    Each process passes its own ``global_batch/process_count`` rows;
    ``jax.make_array_from_process_local_data`` stitches them into a global
    array sharded by ``batch_spec`` over ``mesh`` without gathering —
    the canonical multi-host feeding path (the single-process reference
    never faced this; SURVEY.md §7 "multi-host correctness").
    """

    def lift(x):
        if x is None:
            return None
        sharding = NamedSharding(mesh, batch_spec)
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    return jax.tree_util.tree_map(lift, local_batch)


@dataclasses.dataclass
class DataLoader:
    """Seeded, shard-aware iterator of global TextBatches.

    Each epoch draws a fresh permutation of window indices from a
    ``numpy`` RNG seeded by ``(seed, epoch)``; process ``p`` of ``P`` takes
    rows ``p::P`` of every batch — disjoint coverage with no coordination.
    """

    dataset: TokenDataset
    mesh: Mesh
    global_batch_size: int
    seed: int = 0
    batch_spec: P = P("data")
    # held-out evaluation: the LAST ``round(holdout_fraction * num_windows)``
    # windows of the stream never enter the train split.  ``split="train"``
    # samples the head, ``split="eval"`` (see :meth:`eval_view`) the tail —
    # disjoint window sets (tests/test_data.py); adjacent windows share one
    # boundary token (window i spans [i*s, i*s+s] inclusive), so exactly one
    # context token leaks across the split — eval *targets* never appear as
    # train targets.
    holdout_fraction: float = 0.0
    split: str = "train"

    def __post_init__(self):
        self.process_count = jax.process_count()
        self.process_index = jax.process_index()
        if self.global_batch_size % self.process_count:
            raise ValueError(
                f"global batch {self.global_batch_size} not divisible by "
                f"process count {self.process_count}"
            )
        self.local_batch_size = self.global_batch_size // self.process_count
        if not 0.0 <= self.holdout_fraction < 1.0:
            raise ValueError(f"holdout_fraction={self.holdout_fraction} not in [0, 1)")
        n_eval = int(round(self.dataset.num_windows * self.holdout_fraction))
        if self.split == "train":
            self._window_offset = 0
            self.num_windows = self.dataset.num_windows - n_eval
        elif self.split == "eval":
            if n_eval == 0:
                raise ValueError(
                    "split='eval' needs holdout_fraction > 0 (no held-out windows)"
                )
            self._window_offset = self.dataset.num_windows - n_eval
            self.num_windows = n_eval
        else:
            raise ValueError(f"unknown split {self.split!r}")
        if self.num_windows < self.global_batch_size:
            raise ValueError(
                f"{self.split} split has {self.num_windows} windows — fewer "
                f"than one global batch of {self.global_batch_size}"
            )

    def eval_view(self) -> "DataLoader":
        """The held-out counterpart of this loader (same stream, disjoint tail)."""
        return dataclasses.replace(self, split="eval")

    @property
    def batches_per_epoch(self) -> int:
        return self.num_windows // self.global_batch_size

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if getattr(self, "_order_epoch", None) != epoch:
            self._order_epoch = epoch
            self._order = np.random.default_rng((self.seed, epoch)).permutation(
                self.num_windows
            )
        return self._order

    def batch_at(self, step: int) -> TextBatch:
        """The batch for absolute training step ``step`` (0-based).

        Pure function of ``(seed, step)`` — the contract that makes
        checkpoint resume and failure rollback replay the exact data order
        (``Trainer.fit`` feeds from this when given a loader).
        """
        epoch, b = divmod(step, self.batches_per_epoch)
        order = self._epoch_order(epoch)
        rows = order[b * self.global_batch_size : (b + 1) * self.global_batch_size]
        rows = rows + self._window_offset
        local = rows[self.process_index :: self.process_count]
        return make_global_batch(
            self.dataset.batch(local), self.mesh, self.batch_spec
        )

    def epoch(self, epoch: int) -> Iterator[TextBatch]:
        for b in range(self.batches_per_epoch):
            yield self.batch_at(epoch * self.batches_per_epoch + b)

    def __iter__(self) -> Iterator[TextBatch]:
        step = 0
        while True:
            yield self.batch_at(step)
            step += 1

    def prefetch(self, lookahead: int = 2) -> Iterator[TextBatch]:
        """Iterate with ``lookahead`` batches assembled ahead of consumption.

        ``make_global_batch`` dispatches host-to-device transfers
        asynchronously, so holding the next batches in flight overlaps
        window assembly + H2D with the device's current step — the standard
        input-pipeline trick the reference (inline random tensors) never
        needed.  ``lookahead <= 0`` degrades to plain iteration.
        """
        import collections
        import itertools

        it = iter(self)
        if lookahead <= 0:
            return it

        def gen():
            queue = collections.deque(itertools.islice(it, lookahead))
            while queue:
                yield queue.popleft()
                nxt = next(it, None)
                if nxt is not None:
                    queue.append(nxt)

        return gen()
