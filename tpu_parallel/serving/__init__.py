"""Continuous-batching serving: iteration-level scheduling over a slot
pool of KV caches, with a bucketed/batched/chunked prefill fast path,
prefix reuse, and exact speculative (draft-verify) decoding
(docs/10_serving_engine.md)."""

from tpu_parallel.serving.cache_pool import (
    BlockAllocator,
    CachePool,
    PagedCachePool,
    clear_rows,
    copy_prefix_rows,
    extract_rows,
    insert_rows,
    scatter_rows,
)
from tpu_parallel.serving.engine import (
    ServingEngine,
    default_prefill_buckets,
    sample_tokens,
)
from tpu_parallel.serving.kv_hierarchy import (
    MIGRATION_STATUSES,
    KVPrefixExport,
    RadixPrefixCache,
)
from tpu_parallel.serving.metrics import ServingMetrics, percentile
from tpu_parallel.serving.prefix_cache import PrefixCache
from tpu_parallel.serving.request import (
    CANCELLED,
    EXPIRED,
    FAILED,
    FINISHED,
    QUEUED,
    REJECT_CAPACITY,
    REJECT_CLIENT_LIMIT,
    REJECT_DRAINING,
    REJECT_QUEUE_FULL,
    REJECT_SHED,
    REJECT_TOKEN_BUDGET,
    REJECTED,
    RUNNING,
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from tpu_parallel.serving.scheduler import (
    FIFOScheduler,
    SchedulerConfig,
    SubmitResult,
)
from tpu_parallel.serving.spec_decode import (
    Drafter,
    NGramDrafter,
    adapt_draft_len,
    generate_speculative,
    greedy_verify,
    rejection_verify,
    verify_tokens,
)

__all__ = [
    "BlockAllocator",
    "CachePool",
    "PagedCachePool",
    "insert_rows",
    "scatter_rows",
    "extract_rows",
    "clear_rows",
    "copy_prefix_rows",
    "ServingEngine",
    "default_prefill_buckets",
    "sample_tokens",
    "ServingMetrics",
    "percentile",
    "PrefixCache",
    "RadixPrefixCache",
    "KVPrefixExport",
    "MIGRATION_STATUSES",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "StreamEvent",
    "QUEUED",
    "RUNNING",
    "FINISHED",
    "REJECTED",
    "EXPIRED",
    "CANCELLED",
    "FAILED",
    "REJECT_QUEUE_FULL",
    "REJECT_DRAINING",
    "REJECT_CAPACITY",
    "REJECT_TOKEN_BUDGET",
    "REJECT_CLIENT_LIMIT",
    "REJECT_SHED",
    "FIFOScheduler",
    "SchedulerConfig",
    "SubmitResult",
    "Drafter",
    "NGramDrafter",
    "adapt_draft_len",
    "generate_speculative",
    "greedy_verify",
    "rejection_verify",
    "verify_tokens",
]
