"""Synthetic data generators for tests and benchmarks.

Capability parity: the reference's inline synthetic batches
(``data_paral.py:113-124``, ``param_sharding.py:276-287``) — with the intent
implemented correctly: integer labels come from ``jax.random.randint`` (the
reference drew them from ``normal`` with the wrong signature, bug #4 in
SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from tpu_parallel.core.state import Batch, TextBatch


def classification_batch(
    rng: jax.Array, batch_size: int, input_size: int, num_classes: int
) -> Batch:
    k_in, k_lbl = jax.random.split(rng)
    return Batch(
        inputs=jax.random.normal(k_in, (batch_size, input_size)),
        labels=jax.random.randint(k_lbl, (batch_size,), 0, num_classes),
    )


def lm_batch(
    rng: jax.Array, batch_size: int, seq_len: int, vocab_size: int
) -> TextBatch:
    """Next-token-prediction batch from a random token stream."""
    tokens = jax.random.randint(rng, (batch_size, seq_len + 1), 0, vocab_size)
    return TextBatch(
        tokens=tokens[:, :-1],
        targets=tokens[:, 1:],
        loss_mask=jnp.ones((batch_size, seq_len), jnp.float32),
        positions=jnp.broadcast_to(jnp.arange(seq_len), (batch_size, seq_len)),
    )
