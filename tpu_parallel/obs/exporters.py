"""Pluggable telemetry exporters: Chrome trace-event JSON (Perfetto),
Prometheus text exposition, and the shared JSONL sink.

All three read the SAME two sources — a :class:`~tpu_parallel.obs.tracer.
Tracer`'s span list and a :class:`~tpu_parallel.obs.registry.
MetricRegistry` snapshot — so adding an exporter never means adding
instrumentation.

Chrome trace mapping: one trace **process** per export, one **thread**
(tid) per tracer track — the serving engine's layout comes out as one
row per cache slot plus a ``scheduler`` row, which is exactly how
Perfetto renders a slot pool legibly.  Complete spans emit ``X`` events;
async spans (overlapping queue waits) emit ``b``/``e`` nestable pairs
keyed by request id; instants emit thread-scoped ``i`` markers.
Timestamps are microseconds (the trace-event contract).
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Union

from tpu_parallel.obs.registry import MetricRegistry
from tpu_parallel.obs.tracer import Tracer

# -- Chrome trace-event JSON (Perfetto / chrome://tracing) -----------------


def chrome_trace_events(tracer: Tracer, pid: int = 1) -> List[Dict]:
    """Flatten a tracer into trace-event dicts (metadata + spans +
    instants).  Unfinished spans close at the last timestamp seen, so a
    trace from an aborted run still loads."""
    events: List[Dict] = [
        {
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "tpu_parallel"},
        }
    ]
    tids = {track: i for i, track in enumerate(tracer.tracks())}
    for track, tid in tids.items():
        events.append(
            {
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": track},
            }
        )
        # tid order == tracks() order (scheduler first, slots sorted)
        events.append(
            {
                "ph": "M", "name": "thread_sort_index", "pid": pid,
                "tid": tid, "args": {"sort_index": tid},
            }
        )
    ends = [s.end for s in tracer.spans if s.end is not None]
    ends += [s.start for s in tracer.spans]
    ends += [ev["ts"] for ev in tracer.instants]
    last_ts = max(ends) if ends else 0.0
    for span in tracer.spans:
        tid = tids[span.track]
        start_us = span.start * 1e6
        end = span.end if span.end is not None else last_ts
        args = dict(span.attrs)
        if span.async_id is not None:
            common = {
                "cat": "async", "id": str(span.async_id),
                "name": span.name, "pid": pid, "tid": tid,
            }
            events.append({"ph": "b", "ts": start_us, "args": args, **common})
            events.append({"ph": "e", "ts": end * 1e6, **common})
        else:
            events.append(
                {
                    "ph": "X", "cat": "span", "name": span.name,
                    "pid": pid, "tid": tid, "ts": start_us,
                    "dur": max(0.0, (end - span.start) * 1e6),
                    "args": args,
                }
            )
    for ev in tracer.instants:
        events.append(
            {
                "ph": "i", "s": "t", "cat": "instant", "name": ev["name"],
                "pid": pid, "tid": tids[ev["track"]], "ts": ev["ts"] * 1e6,
                "args": dict(ev["attrs"]),
            }
        )
    return events


def write_chrome_trace(tracer: Tracer, path: str) -> str:
    """Dump the tracer as a Perfetto-openable trace file; returns
    ``path``."""
    with open(path, "w") as fh:
        json.dump(
            {
                "traceEvents": chrome_trace_events(tracer),
                "displayTimeUnit": "ms",
            },
            fh,
        )
    return path


# -- Prometheus text exposition --------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    name = _NAME_OK.sub("_", name)
    return name if not name[:1].isdigit() else "_" + name


def _prom_labels(labels: Dict[str, str], extra: Dict[str, str] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(
        '{}="{}"'.format(
            _prom_name(k),
            str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n"),
        )
        for k, v in sorted(items.items())
    )
    return "{" + body + "}"


def _prom_value(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def prometheus_lines(snapshot: Dict) -> List[str]:
    """Render a registry snapshot as Prometheus text-exposition lines
    (``# TYPE`` headers + one sample per line; histograms expand to
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``)."""
    lines: List[str] = []
    typed: set = set()

    def header(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for row in snapshot.get("counters", []):
        name = _prom_name(row["name"])
        header(name, "counter")
        lines.append(
            f"{name}{_prom_labels(row['labels'])} {_prom_value(row['value'])}"
        )
    for row in snapshot.get("gauges", []):
        name = _prom_name(row["name"])
        header(name, "gauge")
        lines.append(
            f"{name}{_prom_labels(row['labels'])} {_prom_value(row['value'])}"
        )
    for row in snapshot.get("histograms", []):
        name = _prom_name(row["name"])
        header(name, "histogram")
        labels = row["labels"]
        for edge, cum in row["buckets"]:
            lines.append(
                f"{name}_bucket"
                f"{_prom_labels(labels, {'le': _prom_value(edge)})} {cum}"
            )
        lines.append(
            f"{name}_bucket{_prom_labels(labels, {'le': '+Inf'})} "
            f"{row['count']}"
        )
        lines.append(
            f"{name}_sum{_prom_labels(labels)} {_prom_value(row['sum'])}"
        )
        lines.append(f"{name}_count{_prom_labels(labels)} {row['count']}")
    return lines


def prometheus_text(source: Union[MetricRegistry, Dict]) -> str:
    snap = source.snapshot() if isinstance(source, MetricRegistry) else source
    return "\n".join(prometheus_lines(snap)) + "\n"


def _prom_unescape(value: str) -> str:
    """Invert the exposition-format label-value escaping (backslash,
    double-quote, newline) — a left-to-right scan, NOT chained
    ``str.replace`` (which would corrupt ``\\\\n`` into a newline)."""
    out: List[str] = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ('"', "\\"):
                out.append(nxt)
            else:  # unknown escape: keep it verbatim
                out.append(ch)
                out.append(nxt)
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)\s*$"
)
_LABEL_RE = re.compile(
    r'\s*(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"\s*(?:,|$)'
)


def parse_prometheus_text(text: str) -> List[Dict]:
    """Parse text exposition back into samples:
    ``[{"name", "labels", "value", "type"}]``.  The label values are
    UNescaped, so this round-trips :func:`prometheus_lines` exactly —
    the fleet aggregator relabels peer series through it, and the
    round-trip is the escaping regression test's oracle.  Unparseable
    lines raise ``ValueError`` (an aggregator must not silently drop a
    peer's series)."""
    samples: List[Dict] = []
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {raw!r}")
        labels: Dict[str, str] = {}
        body = m.group("labels")
        if body:
            pos = 0
            while pos < len(body):
                lm = _LABEL_RE.match(body, pos)
                if lm is None:
                    raise ValueError(
                        f"unparseable label body in line: {raw!r}"
                    )
                labels[lm.group("key")] = _prom_unescape(lm.group("val"))
                pos = lm.end()
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                base = name[: -len(suffix)]
                break
        samples.append({
            "name": name,
            "labels": labels,
            "value": float(m.group("value")),
            "type": types.get(base),
        })
    return samples


def write_prometheus(source: Union[MetricRegistry, Dict], path: str) -> str:
    """Write one text-exposition snapshot (node-exporter textfile style —
    point a file scrape at it, or re-export per tick for a live series);
    returns ``path``."""
    with open(path, "w") as fh:
        fh.write(prometheus_text(source))
    return path


# -- JSONL sink (the MetricLogger file every subsystem already writes) -----


def export_snapshot_jsonl(registry: MetricRegistry, logger, **extra) -> Dict:
    """Append one full registry snapshot to a
    :class:`~tpu_parallel.utils.logging_utils.MetricLogger` JSONL sink
    (process-0-gated by the logger) — the existing machine-readable
    stream, rebased onto the registry instead of ad-hoc dicts.  Returns
    the record written."""
    record = {"kind": "registry_snapshot", **extra,
              "metrics": registry.snapshot()}
    logger.log_record(record)
    return record
