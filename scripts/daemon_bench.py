"""Daemon crash/drain soak: kill -9 the serving process mid-traffic,
restart it, and PROVE the journal-replay contract — and, under
``--disk-faults``, prove the INTEGRITY contract: seeded media
corruption (kill-torn tails, post-fsync bit rot, persistent fsync
``EIO``) is always either typed-detected or bitwise-recomputed — zero
silent wrong tokens, zero lost accepted requests.

Entry modes:

- (default) ``--soak``: the acceptance gate.  For each seeded trial:
  start the daemon as a real subprocess, feed it a seeded request
  schedule over HTTP (every request carries a client dedupe token),
  SIGKILL the process at a seeded point mid-traffic, restart it on the
  SAME journal, retry every submission idempotently (real clients retry
  on connection loss), run the remainder out, and assert:

  1. **zero lost accepted requests** — every journaled submit reaches
     exactly one ``finished`` terminal across the two process lives;
  2. **zero duplicate completions** — each dedupe token maps to exactly
     one journal submit and one terminal (retries after the crash
     dedupe instead of re-admitting);
  3. **bitwise token parity** — every completed stream equals the
     static greedy reference, so the crash+replay (journal prefix +
     forced-prefix recompute) changed NOTHING about the output;
  4. **zero leaked KV reservations** — ``/statez`` shows
     ``inflight_tokens == 0`` and every replica's slots/queues empty
     after quiesce;
  5. **graceful exit** — SIGTERM drains and exits 0 inside the grace
     window, with a clean shutdown record as the journal's last word.

  ``--record DAEMON_r01.json`` writes the per-trial evidence.

- ``--disk-faults SEED``: the media-integrity soak.  Per seeded trial:
  (a) life 1 accepts traffic and is SIGKILLed mid-stream; (b) the
  harness flips ONE seeded bit inside the journal's last complete
  record — post-fsync bit rot, the damage the per-record CRC exists
  for; (c) life 2 restarts on the corrupted journal: the CRC-failed
  tail record must be TRUNCATED (typed detection, never silent
  replay), every surviving request recovers and finishes BITWISE
  against the greedy reference, and a request whose submit record was
  the corrupted one re-admits through the idempotent client retry;
  (d) a separate DEGRADED leg starts a child with an injected
  persistent-``EIO``-on-fsync plan
  (``tpu_parallel/daemon/iofaults.py``): after the error threshold
  the daemon must serve 503s with a typed ``degraded`` reason and a
  ``degraded_reason`` on ``/healthz``, finish its accepted in-flight
  work, and STILL drain exit 0 on SIGTERM.
  ``--record DAEMON_r02.json`` writes the per-trial evidence.

- ``--smoke``: the fast CI gate (wired into ``scripts/check_all.py``
  and tier-1 via ``tests/test_daemon.py``): one subprocess — start,
  healthz, submit over HTTP, stream to completion, SIGTERM, assert a
  clean drained exit 0 and a clean journal.  No kill -9 (that is the
  soak's job); one model build is the whole cost.  ``--disk-smoke``
  is its integrity sibling (one reduced ``--disk-faults`` trial, no
  degraded leg) — ``check_daemon`` runs both.

- ``--kv-disk SEED``: the SSD-KV-tier acceptance bench
  (``KVDISK_r01.json``).  Life 1 builds a warm set of long shared
  headers through a tight radix+host hierarchy backed by a disk tier
  (``--kv-disk-dir``), forcing cold host evictions to SPILL block
  payloads to per-block-CRC'd files, then is SIGKILLed.  Three
  restart legs on the same schedule: **warm** (same disk directory —
  the manifest must seed prefix chains, every replayed header must
  hydrate through typed disk restores, zero failures, bitwise tokens,
  and TTFT p95 strictly below the **cold** leg, which restarts on an
  EMPTY disk directory with the identical engine shape) and **rot**
  (one seeded bit flipped in every spilled blob — every planted
  corruption must be typed-detected while the replay recomputes
  bitwise; silent wrong tokens are the only failure).  A fourth leg
  delegates the hit-rate comparison (disk-backed vs RAM-only
  hierarchy at a working set far above ``kv_host_blocks``) to
  ``serve_bench.run_kv_disk_bench``.  ``--kv-disk-smoke`` is the
  reduced warm-restart trial ``check_daemon`` runs.

- ``--serve``: INTERNAL child mode — build the tiny-model fleet, wrap
  it in :class:`~tpu_parallel.daemon.ServingDaemon` + HTTP server,
  write the ready file, install signals, pump until shut down, exit
  with ``daemon.run()``'s code.  ``--io-fsync-eio N`` arms the IO
  fault shim with a persistent fsync-``EIO`` plan starting at fsync
  index N.  ``--kv-disk-dir D`` attaches the radix + host + SSD KV
  hierarchy (one subdirectory per replica).  The parent modes spawn
  this.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

DEFAULT_NEW_TOKENS = 8
SOAK_NEW_TOKENS = 20  # long enough that a seeded kill lands mid-stream
READY_TIMEOUT = 300.0  # cold jax import + compile on a 1-core box

# --kv-disk geometry: the soak/crash modes keep the 32-token toy model
# (prefill there is pure dispatch), but the SSD tier's TTFT claim needs
# prefill COMPUTE to save — so its legs run a small-but-real model
# (serve_bench's hierarchy-bench trick) with 3-block shared headers and
# a hierarchy tight enough that the working set can only live on disk.
# d_model=512 puts a 96-token prefill at ~30 ms of CPU compute while a
# 3-blob chain restore is a few ms of IO — the warm/cold gap must be
# compute, not scheduler noise; disk capacity holds every soak chain
# (20 headers + warmup + flushers, 3 blocks each) with headroom so the
# warm leg never loses a chain to disk-tier eviction
KV_DISK_MODEL = dict(d_model=512, n_layers=4, n_heads=4, seq_len=128)
KV_DISK_ENGINE = dict(
    kv_block_tokens=32, kv_pool_blocks=24, prefix_cache_size=4,
    kv_radix_cache=True, kv_host_blocks=4, kv_disk_blocks=160,
)
KV_DISK_HEADER_TOKENS = 96  # 3 full blocks of reusable tenant header
KV_DISK_NEW_TOKENS = 6


# -- HTTP client helpers -----------------------------------------------------


def http_json(method, url, body=None, timeout=120.0):
    """One JSON request; returns (status_code, payload) and never
    raises on HTTP error codes (connection errors DO raise)."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


def wait_ready(ready_file, proc, timeout=READY_TIMEOUT):
    """Poll for the child's ready file; returns its payload dict."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon child exited rc={proc.returncode} before ready"
            )
        if os.path.exists(ready_file):
            try:
                with open(ready_file) as fh:
                    info = json.load(fh)
                if "port" in info:
                    return info
            except (ValueError, OSError):
                pass  # mid-write
        time.sleep(0.05)
    raise RuntimeError(f"daemon child not ready within {timeout}s")


def spawn_daemon(args, journal, ready_file, extra=()):
    """Start the --serve child with this script's interpreter/env."""
    if os.path.exists(ready_file):
        os.remove(ready_file)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--serve",
        "--journal", journal, "--ready-file", ready_file,
        "--replicas", str(args.replicas), "--slots", str(args.slots),
        "--grace", str(args.grace), "--fsync-batch", str(args.fsync_batch),
        *extra,
    ]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(cmd, env=env)


# -- schedule + references ---------------------------------------------------


def make_schedule(seed, n_requests, new_tokens):
    """Seeded prompts + dedupe tokens (pure function of seed)."""
    rnd = random.Random(seed)
    schedule = []
    for i in range(n_requests):
        length = rnd.randrange(3, 12)
        prompt = [rnd.randrange(1, 250) for _ in range(length)]
        schedule.append({
            "dedupe_token": f"soak-{seed}-{i}",
            "prompt": prompt,
            "max_new_tokens": new_tokens,
        })
    return schedule


def greedy_references(schedule, cfg_overrides=None):
    """Static-generate greedy continuation for every prompt — the
    parity oracle the daemon's crash+replay output must match.
    ``cfg_overrides`` must mirror what the ``--serve`` child builds
    (the ``--kv-disk`` legs use :data:`KV_DISK_MODEL`)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_parallel.models import GPTLM, tiny_test
    from tpu_parallel.models.generate import generate

    cfg = tiny_test(remat=False, **(cfg_overrides or {}))
    model = GPTLM(cfg)
    probe = jnp.zeros((1, 16), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    refs = {}
    for entry in schedule:
        prompt = entry["prompt"]
        # generate() returns [batch, max_new_tokens] — continuation only
        cont = np.asarray(generate(
            model, params, jnp.asarray(prompt, jnp.int32)[None, :],
            max_new_tokens=entry["max_new_tokens"],
        ))[0]
        refs[entry["dedupe_token"]] = [int(t) for t in cont]
    return refs


# -- the serve child ---------------------------------------------------------


def serve(args):
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(REPO_ROOT, ".pytest_xla_cache"),
    )
    from tpu_parallel.cluster import Frontend, FrontendConfig
    from tpu_parallel.daemon import (
        DaemonConfig,
        DaemonHTTPServer,
        ServingDaemon,
    )
    from tpu_parallel.daemon import iofaults

    if args.io_fsync_eio >= 0:
        # the dead-disk shape: every fsync from index N on fails EIO —
        # the child must DEGRADE (typed 503s, /healthz reason), not die
        iofaults.install(iofaults.IOFaultPlan(
            fsync_eio_at=args.io_fsync_eio,
            fsync_eio_count=iofaults.PERSISTENT,
        ))
    from tpu_parallel.models import GPTLM, tiny_test
    from tpu_parallel.obs.registry import MetricRegistry
    from tpu_parallel.serving import SchedulerConfig, ServingEngine

    cfg = tiny_test(
        remat=False, **(KV_DISK_MODEL if args.kv_disk_dir else {})
    )
    model = GPTLM(cfg)
    probe = jax.numpy.zeros((1, 16), jax.numpy.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]

    def frontend_factory(clock):
        engines = []
        for i in range(args.replicas):
            extra_kw = {}
            if args.kv_disk_dir:
                # one store per replica: the manifest journal is a
                # single-writer file, so replicas must not share a root
                extra_kw = dict(
                    KV_DISK_ENGINE,
                    kv_disk_dir=os.path.join(args.kv_disk_dir, f"r{i}"),
                )
            engines.append(ServingEngine(
                model, params, n_slots=args.slots,
                scheduler=SchedulerConfig(max_prefills_per_tick=2),
                **extra_kw,
            ))
        return Frontend(
            engines, router="least",
            config=FrontendConfig(restart=None),
            clock=clock, registry=MetricRegistry(),
        )

    daemon = ServingDaemon(
        frontend_factory, args.journal,
        config=DaemonConfig(
            grace_seconds=args.grace, fsync_batch=args.fsync_batch,
        ),
    )
    server = DaemonHTTPServer(daemon, port=args.port).start()
    daemon.install_signals()
    with open(args.ready_file + ".tmp", "w") as fh:
        json.dump({"port": server.port, "pid": os.getpid()}, fh)
    os.replace(args.ready_file + ".tmp", args.ready_file)
    rc = daemon.run()
    server.stop()
    return rc


# -- invariants --------------------------------------------------------------


def journal_invariants(journal_path, problems):
    """Scan the journal the way recovery does and check the no-loss /
    no-duplicate bookkeeping.  Returns the folded state."""
    from tpu_parallel.daemon import load_state

    state = load_state(journal_path)
    by_token = {}
    for rid in state.order:
        entry = state.entries[rid]
        tok = entry.dedupe_token
        if tok is not None:
            by_token.setdefault(tok, []).append(rid)
    for tok, rids in by_token.items():
        if len(rids) != 1:
            problems.append(
                f"dedupe token {tok} journaled {len(rids)} submits "
                f"({rids}) — duplicate admission"
            )
    for entry in state.unfinished:
        problems.append(
            f"request {entry.request_id} journaled accepted but never "
            "reached a terminal — lost accepted work"
        )
    return state


def state_leak_check(port, problems, label):
    code, payload = http_json(
        "GET", f"http://127.0.0.1:{port}/statez"
    )
    if code != 200:
        problems.append(f"{label}: /statez returned {code}")
        return
    cluster = payload["cluster"]
    if cluster["inflight_tokens"] != 0:
        problems.append(
            f"{label}: leaked token reservations: "
            f"{cluster['inflight_tokens']}"
        )
    for rep in cluster["replicas"]:
        if rep["active_slots"] or rep["queue_depth"]:
            problems.append(
                f"{label}: replica {rep['replica']} not quiesced: "
                f"slots={rep['active_slots']} queue={rep['queue_depth']}"
            )


def stop_gracefully(proc, grace, problems, label):
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=grace + 60)
    except subprocess.TimeoutExpired:
        proc.kill()
        problems.append(f"{label}: SIGTERM did not exit within grace")
        return
    if rc != 0:
        problems.append(f"{label}: drain exit code {rc} != 0")


# -- modes -------------------------------------------------------------------


def run_smoke(tmpdir=None, keep=False):
    """start -> submit -> stream -> SIGTERM drain -> clean exit.  The
    fast gate check_all and tier-1 run.  Returns a problem list."""
    import tempfile

    from tpu_parallel.daemon import REC_SHUTDOWN, read_journal

    problems = []
    tmpdir = tmpdir or tempfile.mkdtemp(prefix="daemon_smoke_")
    journal = os.path.join(tmpdir, "journal.jsonl")
    ready = os.path.join(tmpdir, "ready.json")
    args = argparse.Namespace(
        replicas=1, slots=2, grace=60.0, fsync_batch=8,
    )
    proc = spawn_daemon(args, journal, ready)
    try:
        info = wait_ready(ready, proc)
        port = info["port"]
        code, payload = http_json(
            "GET", f"http://127.0.0.1:{port}/healthz"
        )
        if code != 200 or not payload.get("ok"):
            problems.append(f"healthz {code}: {payload}")
        schedule = make_schedule(seed=7, n_requests=2,
                                 new_tokens=DEFAULT_NEW_TOKENS)
        rids = []
        for entry in schedule:
            code, rec = http_json(
                "POST", f"http://127.0.0.1:{port}/v1/submit", entry
            )
            if code != 200:
                problems.append(f"submit {code}: {rec}")
                continue
            rids.append(rec["request_id"])
        # idempotence: resubmitting the first token dedupes
        code, rec = http_json(
            "POST", f"http://127.0.0.1:{port}/v1/submit", schedule[0]
        )
        if code != 200 or rec["request_id"] != rids[0]:
            problems.append(f"dedupe resubmit mismatched: {code} {rec}")
        deadline = time.monotonic() + 120
        for rid in rids:
            while time.monotonic() < deadline:
                code, rec = http_json(
                    "GET", f"http://127.0.0.1:{port}/v1/result/{rid}"
                )
                if code == 200 and rec["status"] == "finished":
                    if len(rec["tokens"]) != DEFAULT_NEW_TOKENS:
                        problems.append(
                            f"{rid}: {len(rec['tokens'])} tokens != "
                            f"{DEFAULT_NEW_TOKENS}"
                        )
                    break
                time.sleep(0.05)
            else:
                problems.append(f"{rid}: never finished")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metricsz", timeout=30
        ) as resp:
            metrics_text = resp.read().decode()
        if "daemon_journal_records_total" not in metrics_text:
            problems.append("metricsz missing daemon_* series")
        if rids:
            # SSE replay of a finished stream: N token events + a
            # finished event with the typed reason
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/stream/{rids[0]}"
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                events = [
                    json.loads(line[len(b"data: "):])
                    for line in resp.read().split(b"\n")
                    if line.startswith(b"data: ")
                ]
            toks = [e["token"] for e in events if "token" in e]
            if len(toks) != DEFAULT_NEW_TOKENS or not events[-1].get(
                "finished"
            ):
                problems.append(
                    f"stream replay malformed: {len(toks)} tokens, "
                    f"tail {events[-1] if events else None}"
                )
        state_leak_check(port, problems, "smoke")
        stop_gracefully(proc, args.grace, problems, "smoke")
        records, torn = read_journal(journal)
        if torn:
            problems.append(f"{torn} torn record(s) after a clean exit")
        last = records[-1] if records else {}
        if last.get("record") != REC_SHUTDOWN or not last.get("clean"):
            problems.append(
                f"journal's last word is {last} — expected a clean "
                "shutdown record"
            )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if not keep and not problems:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    return problems


def corrupt_tail_record(journal_path, rnd):
    """Flip ONE seeded bit inside the journal's last COMPLETE record —
    the post-fsync bit-rot shape the per-record CRC exists to catch.
    (A SIGKILL may also have left an unterminated fragment after it;
    recovery must truncate both.)  Returns ``(record_kind,
    dedupe_token)`` of the corrupted record so the caller knows which
    damage class it planted (a submit's loss re-admits via client
    retry; a tokens/terminal loss regenerates bitwise)."""
    import json as _json

    with open(journal_path, "rb") as fh:
        data = fh.read()
    end = len(data)
    if not data.endswith(b"\n"):
        end = data.rfind(b"\n") + 1  # skip the torn fragment
    start = data.rfind(b"\n", 0, end - 1) + 1
    line = data[start:end - 1]  # the last complete record's bytes
    try:
        rec = _json.loads(line)
    except ValueError:
        rec = {}
    bit = rnd.randrange(len(line) * 8)
    flipped = bytearray(line)
    flipped[bit // 8] ^= 1 << (bit % 8)
    with open(journal_path, "wb") as fh:
        fh.write(data[:start] + bytes(flipped) + data[end - 1:])
    return rec.get("record", "unparseable"), rec.get("dedupe_token")


def run_disk_trial(args, seed, refs, degraded_leg=True):
    """One seeded disk-fault trial (see the module docstring's
    ``--disk-faults`` contract).  Returns (trial_record, problems)."""
    from tpu_parallel.daemon import load_state, read_journal

    rnd = random.Random(seed ^ 0x10FA)
    problems = []
    tmpdir = os.path.join(
        args.workdir or "/tmp", f"daemon_disk_{os.getpid()}_{seed}"
    )
    os.makedirs(tmpdir, exist_ok=True)
    journal = os.path.join(tmpdir, "journal.jsonl")
    ready = os.path.join(tmpdir, "ready.json")
    if os.path.exists(journal):
        os.remove(journal)
    schedule = make_schedule(seed, args.requests, args.new)

    # ---- life 1: accept traffic, SIGKILL mid-stream
    proc = spawn_daemon(args, journal, ready)
    info = wait_ready(ready, proc)
    port = info["port"]
    kill_after = rnd.randrange(2, max(3, args.requests))
    accepted = {}
    for i, entry in enumerate(schedule):
        try:
            code, rec = http_json(
                "POST", f"http://127.0.0.1:{port}/v1/submit", entry
            )
        except (urllib.error.URLError, OSError):
            break
        if code == 200:
            accepted[entry["dedupe_token"]] = rec["request_id"]
        if i + 1 == kill_after:
            time.sleep(rnd.uniform(0.2, 0.6))  # let tokens stream
            break
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)

    # ---- seeded media corruption: one bit of the last durable record
    kind, corrupted_token = corrupt_tail_record(journal, rnd)
    pre_records = None
    try:
        pre_records, pre_torn = read_journal(journal)
    except Exception as exc:
        # the flip landed in the LAST record, so a typed torn-tail read
        # must still succeed — anything else is a detection bug
        problems.append(
            f"read_journal refused a tail-corrupted journal: {exc!r}"
        )
        pre_torn = -1
    if pre_torn == 0:
        problems.append(
            "planted bit flip was not detected as tail damage "
            f"(corrupted a {kind} record)"
        )

    # ---- life 2: restart on the corrupted journal; idempotent retries
    proc = spawn_daemon(args, journal, ready)
    info = wait_ready(ready, proc)
    port = info["port"]
    # the CRC-failed record must be GONE (truncated), not tolerated
    # forever: the restarted journal parses torn-free end to end
    records, torn = read_journal(journal)
    if torn:
        problems.append(
            f"life2: {torn} damaged record(s) survived the restart "
            "truncation"
        )
    dedupe_hits = 0
    readmitted = 0
    all_rids = {}
    for entry in schedule:
        code, rec = http_json(
            "POST", f"http://127.0.0.1:{port}/v1/submit", entry
        )
        if code != 200:
            problems.append(f"life2 submit rejected {code}: {rec}")
            continue
        tok = entry["dedupe_token"]
        all_rids[tok] = rec["request_id"]
        if tok in accepted:
            if rec["request_id"] == accepted[tok]:
                dedupe_hits += 1
            elif tok == corrupted_token:
                # the corrupted record WAS this submit: its durability
                # was lost with the bit, so the retry legitimately
                # re-admits fresh — the typed, counted fallback
                readmitted += 1
            else:
                problems.append(
                    f"life2: dedupe {tok} re-admitted as "
                    f"{rec['request_id']} != {accepted[tok]} (corrupted "
                    f"record was {kind})"
                )
    deadline = time.monotonic() + 240
    finished = {}
    pending = dict(all_rids)
    while pending and time.monotonic() < deadline:
        for tok, rid in list(pending.items()):
            code, rec = http_json(
                "GET", f"http://127.0.0.1:{port}/v1/result/{rid}"
            )
            if code == 200 and rec["status"] in (
                "finished", "failed", "cancelled", "rejected", "expired",
            ):
                finished[tok] = rec
                del pending[tok]
        time.sleep(0.05)
    for tok, rid in pending.items():
        problems.append(f"{tok} ({rid}): never terminal")
    for tok, rec in finished.items():
        if rec["status"] != "finished":
            problems.append(
                f"{tok}: status {rec['status']} ({rec['finish_reason']})"
                " — lost accepted work"
            )
        elif rec["tokens"] != refs[tok]:
            problems.append(
                f"{tok}: tokens diverge from the greedy reference "
                "through crash + media corruption (SILENT WRONG TOKENS)"
            )
    state_leak_check(port, problems, f"disk{seed}")
    stop_gracefully(proc, args.grace, problems, f"disk{seed}")
    state = journal_invariants(journal, problems)
    trial = {
        "seed": seed,
        "kill_after": kill_after,
        "corrupted_record": kind,
        "corrupted_submit_readmitted": readmitted,
        "dedupe_hits_on_retry": dedupe_hits,
        "recoveries": state.recoveries,
        "finished": sum(
            1 for r in finished.values() if r["status"] == "finished"
        ),
        "requests": args.requests,
    }

    # ---- degraded leg: persistent fsync EIO -> typed 503s, clean drain
    if degraded_leg:
        dj = os.path.join(tmpdir, "degraded.jsonl")
        if os.path.exists(dj):
            os.remove(dj)
        proc = spawn_daemon(
            args, dj, ready, extra=("--io-fsync-eio", "3")
        )
        info = wait_ready(ready, proc)
        port = info["port"]
        deg_accepted = []
        saw_degraded = False
        for i, entry in enumerate(make_schedule(
            seed ^ 0xDE6, args.requests, args.new
        )):
            code, rec = http_json(
                "POST", f"http://127.0.0.1:{port}/v1/submit", entry
            )
            if code == 200:
                deg_accepted.append(rec["request_id"])
            elif code == 503 and rec.get("finish_reason") in (
                "degraded", "journal_error"
            ):
                if rec.get("finish_reason") == "degraded":
                    saw_degraded = True
            else:
                problems.append(
                    f"degraded leg: submit {i} -> {code} {rec} (want "
                    "200 or typed 503)"
                )
            time.sleep(0.05)
        deadline = time.monotonic() + 60
        reason = None
        while time.monotonic() < deadline:
            code, health = http_json(
                "GET", f"http://127.0.0.1:{port}/healthz"
            )
            reason = health.get("degraded_reason")
            if code == 503 and reason:
                break
            time.sleep(0.1)
        if not reason:
            problems.append(
                "degraded leg: /healthz never exposed degraded_reason "
                "under persistent fsync EIO"
            )
        if not saw_degraded:
            problems.append(
                "degraded leg: no submission was refused with the "
                "typed 'degraded' reason"
            )
        # accepted-before-degrade work still finishes (drains), and
        # SIGTERM still exits 0 while degraded
        deadline = time.monotonic() + 120
        for rid in deg_accepted:
            while time.monotonic() < deadline:
                code, rec = http_json(
                    "GET", f"http://127.0.0.1:{port}/v1/result/{rid}"
                )
                if code == 200 and rec["status"] == "finished":
                    break
                time.sleep(0.05)
            else:
                problems.append(
                    f"degraded leg: accepted {rid} never finished "
                    "draining"
                )
        stop_gracefully(
            proc, args.grace, problems, f"degraded{seed}"
        )
        trial["degraded"] = {
            "accepted_before_degrade": len(deg_accepted),
            "degraded_reason": reason,
            "typed_degraded_rejects": saw_degraded,
        }
        # the degraded journal is NOT required to be clean (its disk
        # was dying) — but it must never brick: a fresh scan tolerates
        # at most tail damage
        try:
            load_state(dj)
        except Exception as exc:
            problems.append(
                f"degraded leg: journal bricked after EIO storm: "
                f"{exc!r}"
            )
    if not problems:
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    return trial, problems


def run_disk_soak(args):
    """The seeded media-corruption acceptance soak (>= 3 seeds)."""
    record = {"bench": "daemon_disk_faults", "trials": []}
    problems = []
    refs_cache = {}
    for trial in range(args.trials):
        seed = args.disk_faults + trial
        schedule = make_schedule(seed, args.requests, args.new)
        if seed not in refs_cache:
            refs_cache[seed] = greedy_references(schedule)
        trial_rec, trial_problems = run_disk_trial(
            args, seed, refs_cache[seed]
        )
        trial_rec["problems"] = list(trial_problems)
        record["trials"].append(trial_rec)
        problems.extend(trial_problems)
        print(
            f"disk trial {trial} (seed {seed}): "
            f"corrupted={trial_rec['corrupted_record']} "
            f"dedupe_hits={trial_rec['dedupe_hits_on_retry']} "
            f"finished={trial_rec['finished']}/{args.requests} "
            f"degraded_reason="
            f"{trial_rec.get('degraded', {}).get('degraded_reason')} "
            f"problems={len(trial_problems)}"
        )
    record["ok"] = not problems
    if args.record:
        with open(args.record, "w") as fh:
            json.dump(record, fh, indent=2)
            fh.write("\n")
        print(f"record: {args.record}")
    return problems


def run_disk_smoke():
    """One reduced disk-fault trial (no degraded leg): the integrity
    half of the ``check_daemon`` runtime gate."""
    args = argparse.Namespace(
        replicas=1, slots=2, grace=60.0, fsync_batch=4,
        requests=3, new=8, workdir="", record="",
    )
    seed = 5
    schedule = make_schedule(seed, args.requests, args.new)
    refs = greedy_references(schedule)
    _, problems = run_disk_trial(args, seed, refs, degraded_leg=False)
    return problems


# -- SSD KV tier legs (--kv-disk) --------------------------------------------


def make_kv_disk_schedule(seed, n_headers, life,
                          new_tokens=KV_DISK_NEW_TOKENS):
    """Seeded long-header replay schedule.  Prompts are a pure function
    of ``(seed, i)`` — identical across process lives — while the
    dedupe token carries the ``life`` tag, so a restarted daemon
    re-admits the replay as FRESH work (restore or recompute, never a
    journal dedupe hit that would hide the KV path entirely)."""
    rnd = random.Random(seed ^ 0x55D)
    schedule = []
    for i in range(n_headers):
        header = [
            rnd.randrange(1, 250) for _ in range(KV_DISK_HEADER_TOKENS)
        ]
        suffix = [rnd.randrange(1, 250) for _ in range(2)]
        schedule.append({
            "dedupe_token": f"kvd-{seed}-{i}-{life}",
            "prompt": header + suffix,
            "max_new_tokens": new_tokens,
        })
    return schedule


def kv_disk_references(seed, n_headers):
    """Greedy reference continuations indexed by header number (the
    prompts are life-invariant, so one oracle serves every leg)."""
    sched = make_kv_disk_schedule(seed, n_headers, "ref")
    refs = greedy_references(sched, cfg_overrides=KV_DISK_MODEL)
    return [refs[entry["dedupe_token"]] for entry in sched]


def timed_submit(port, entry):
    """Submit one request and ride its LIVE SSE stream to the end:
    returns ``(ttft_seconds, tokens, status)`` where TTFT is measured
    from just before the submit POST to the first streamed token — the
    client-observed latency the warm/cold legs compare.  The stream is
    drained to the terminal event on purpose: hanging up mid-stream
    would CANCEL the request."""
    t0 = time.monotonic()
    code, rec = http_json(
        "POST", f"http://127.0.0.1:{port}/v1/submit", entry
    )
    if code != 200:
        raise RuntimeError(f"submit {code}: {rec}")
    rid = rec["request_id"]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/stream/{rid}"
    )
    ttft, tokens = None, []
    with urllib.request.urlopen(req, timeout=180) as resp:
        for raw in resp:
            if not raw.startswith(b"data: "):
                continue
            ev = json.loads(raw[len(b"data: "):])
            if "token" in ev:
                if ttft is None:
                    ttft = time.monotonic() - t0
                tokens.append(ev["token"])
            if ev.get("finished"):
                return ttft, tokens, ev.get("status")
    raise RuntimeError(f"stream for {rid} closed before the terminal")


def healthz_kv(port):
    code, payload = http_json("GET", f"http://127.0.0.1:{port}/healthz")
    return (payload.get("kv") or {}) if isinstance(payload, dict) else {}


def p95(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(0.95 * (len(xs) - 1))))]


def corrupt_blob_files(disk_root, rnd):
    """Flip one seeded bit inside the payload region of EVERY spilled
    ``.kvw`` blob under ``disk_root`` — post-fsync SSD rot.  The frame
    CRC + manifest cross-check must type every one; returns the count
    planted."""
    flipped = 0
    for root, _, names in os.walk(disk_root):
        for name in sorted(names):
            if not name.endswith(".kvw"):
                continue
            path = os.path.join(root, name)
            with open(path, "rb") as fh:
                data = bytearray(fh.read())
            if len(data) < 8:
                continue
            pos = rnd.randrange(len(data) // 4, 3 * len(data) // 4)
            data[pos] ^= 1 << rnd.randrange(8)
            with open(path, "wb") as fh:
                fh.write(bytes(data))
            flipped += 1
    return flipped


def run_kv_disk_trial(args, seed, refs, *, timing=True, rot_leg=True):
    """One SSD-tier restart trial (see the module docstring's
    ``--kv-disk`` contract).  Returns ``(trial_record, problems)``."""
    import shutil

    n_headers = len(refs)
    problems = []
    tmpdir = os.path.join(
        args.workdir or "/tmp", f"daemon_kvdisk_{os.getpid()}_{seed}"
    )
    if os.path.exists(tmpdir):
        shutil.rmtree(tmpdir)
    os.makedirs(tmpdir)
    journal = os.path.join(tmpdir, "journal.jsonl")
    ready = os.path.join(tmpdir, "ready.json")
    warm_disk = os.path.join(tmpdir, "disk")
    warm_extra = ("--kv-disk-dir", warm_disk)

    def replay(port, life):
        # compile warm-up OUTSIDE the timed window, both paths: the
        # first dummy submit compiles the full-length prefill bucket
        # (the cold path), the immediate second submit HITS the
        # still-resident chain and compiles the short-tail
        # prefix-hit prefill (the warm path) — so no timed request in
        # either leg pays jit, and the legs compare compute, not
        # compilation
        for rep in range(2):
            timed_submit(port, {
                "dedupe_token": f"kvd-{seed}-warmup-{life}-{rep}",
                "prompt": [3] * (KV_DISK_HEADER_TOKENS + 2),
                "max_new_tokens": KV_DISK_NEW_TOKENS,
            })
        ttfts = []
        for i, entry in enumerate(
            make_kv_disk_schedule(seed, n_headers, life)
        ):
            ttft, tokens, status = timed_submit(port, entry)
            if status != "finished":
                problems.append(f"{life}: header {i} status {status}")
            elif tokens != refs[i]:
                problems.append(
                    f"{life}: header {i} tokens diverge from the "
                    "greedy reference (SILENT WRONG TOKENS)"
                )
            ttfts.append(ttft)
        return ttfts

    # ---- life 1: build the warm set through the spill path, kill -9.
    # Each header is submitted TWICE back to back: the second submission
    # hits the still-resident chain, which is what marks its blocks WARM
    # — only evicted-but-warm blocks spill (a cold one-off drops
    # outright), so without the double-take nothing would ever reach
    # disk.  Then a train of warm FLUSHER prompts (disjoint token space)
    # cycles the device and host tiers, pushing every header block
    # through the cold-host-eviction path — whose prefix-closure spill
    # persists each header's whole chain — before the kill lands.
    proc = spawn_daemon(args, journal, ready, extra=warm_extra)
    info = wait_ready(ready, proc)
    port = info["port"]
    # the warmup header is submitted twice so its blocks go WARM and
    # ride the flusher cascade to disk with everything else — the warm
    # leg's (untimed) warmup submits then exercise the disk-restore
    # machinery's first-use costs OUTSIDE the timed window, exactly as
    # they pre-pay compile for the prefill buckets
    for rep in range(2):
        timed_submit(port, {
            "dedupe_token": f"kvd-{seed}-warmup-a-{rep}",
            "prompt": [3] * (KV_DISK_HEADER_TOKENS + 2),
            "max_new_tokens": KV_DISK_NEW_TOKENS,
        })
    build = [
        make_kv_disk_schedule(seed, n_headers, life)
        for life in ("a0", "a1")
    ]
    for i in range(n_headers):
        for sched in build:  # back to back: the second take must HIT
            _, tokens, status = timed_submit(port, sched[i])
            if status != "finished":
                problems.append(f"life1: header {i} status {status}")
            elif tokens != refs[i]:
                problems.append(
                    f"life1: header {i} tokens diverge from the greedy "
                    "reference"
                )
    frnd = random.Random(seed ^ 0xF1)
    for i in range(4):
        flusher = [250] + [
            frnd.randrange(1, 250)
            for _ in range(KV_DISK_HEADER_TOKENS + 1)
        ]
        for rep in range(2):
            timed_submit(port, {
                "dedupe_token": f"kvd-{seed}-flush-{i}-{rep}",
                "prompt": flusher,
                "max_new_tokens": KV_DISK_NEW_TOKENS,
            })
    kv_life1 = healthz_kv(port)
    if kv_life1.get("disk_blocks_used", 0) < n_headers:
        problems.append(
            f"life1: {kv_life1.get('disk_blocks_used', 0)} disk blocks "
            f"< {n_headers} headers — the warm set never reached the "
            f"disk tier (healthz kv: {kv_life1})"
        )
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)

    if rot_leg:
        # snapshot the on-disk tier BEFORE the warm leg mutates it
        rot_disk = os.path.join(tmpdir, "disk_rot")
        shutil.copytree(warm_disk, rot_disk)

    # ---- warm leg: restart on the SAME journal + SAME disk directory
    proc = spawn_daemon(args, journal, ready, extra=warm_extra)
    info = wait_ready(ready, proc)
    port = info["port"]
    kv_seeded = healthz_kv(port)
    if not kv_seeded.get("disk_seeded_chains"):
        problems.append(
            "warm: restart seeded no prefix chains from the manifest "
            f"(healthz kv: {kv_seeded})"
        )
    warm_ttfts = replay(port, "w")
    kv_warm = healthz_kv(port)
    if kv_warm.get("disk_restores", 0) < n_headers:
        problems.append(
            f"warm: {kv_warm.get('disk_restores', 0)} disk restores < "
            f"{n_headers} replayed warm chains — warm hits recomputed"
        )
    if kv_warm.get("disk_restore_failures", 0):
        problems.append(
            f"warm: {kv_warm['disk_restore_failures']} restore "
            "failures on an uncorrupted disk"
        )
    stop_gracefully(proc, args.grace, problems, f"kvdisk-warm{seed}")

    trial = {
        "seed": seed,
        "headers": n_headers,
        "header_tokens": KV_DISK_HEADER_TOKENS,
        "engine": dict(KV_DISK_ENGINE),
        "life1_kv": kv_life1,
        "warm": {
            "kv": kv_warm,
            "seeded_chains": kv_seeded.get("disk_seeded_chains", 0),
            "ttft_ms": [round(t * 1000, 2) for t in warm_ttfts],
        },
    }

    # ---- cold leg: identical engine shape, EMPTY disk directory —
    # the restart-TTFT baseline the warm leg must beat
    if timing:
        cold_journal = os.path.join(tmpdir, "journal_cold.jsonl")
        cold_disk = os.path.join(tmpdir, "disk_cold")
        proc = spawn_daemon(
            args, cold_journal, ready,
            extra=("--kv-disk-dir", cold_disk),
        )
        info = wait_ready(ready, proc)
        port = info["port"]
        cold_ttfts = replay(port, "c")
        stop_gracefully(
            proc, args.grace, problems, f"kvdisk-cold{seed}"
        )
        warm_p95, cold_p95 = p95(warm_ttfts), p95(cold_ttfts)
        if warm_p95 >= cold_p95:
            problems.append(
                f"warm-restart TTFT p95 {warm_p95 * 1000:.1f}ms is not "
                f"below the cold restart's {cold_p95 * 1000:.1f}ms"
            )
        trial["warm"]["ttft_ms_p95"] = round(warm_p95 * 1000, 2)
        trial["cold"] = {
            "ttft_ms": [round(t * 1000, 2) for t in cold_ttfts],
            "ttft_ms_p95": round(cold_p95 * 1000, 2),
        }

    # ---- rot leg: one seeded bit in every spilled blob; every planted
    # corruption must surface as a TYPED restore failure while the
    # replay recomputes bitwise — never as served wrong tokens
    if rot_leg:
        rnd = random.Random(seed ^ 0xB07)
        n_flipped = corrupt_blob_files(rot_disk, rnd)
        rot_journal = os.path.join(tmpdir, "journal_rot.jsonl")
        proc = spawn_daemon(
            args, rot_journal, ready, extra=("--kv-disk-dir", rot_disk),
        )
        info = wait_ready(ready, proc)
        port = info["port"]
        replay(port, "r")
        kv_rot = healthz_kv(port)
        if n_flipped and not kv_rot.get("disk_restore_failures"):
            problems.append(
                f"rot: {n_flipped} planted blob corruptions, none "
                "typed-detected"
            )
        stop_gracefully(proc, args.grace, problems, f"kvdisk-rot{seed}")
        trial["rot"] = {"flipped_blobs": n_flipped, "kv": kv_rot}

    if not problems:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return trial, problems


def run_kv_disk_soak(args):
    """The SSD-tier acceptance bench: restart-TTFT warm vs cold on the
    same disk, seeded blob rot, plus serve_bench's disk-vs-RAM-only
    hit-rate leg — one ``KVDISK_r01.json`` record."""
    import importlib.util
    import types

    record = {"bench": "kv_disk", "trials": []}
    problems = []
    # 20 timed samples per leg: p95 is the second-worst sample, so one
    # scheduler hiccup cannot decide the warm-vs-cold verdict
    n_headers = 20
    for trial in range(args.trials):
        seed = args.kv_disk + trial
        refs = kv_disk_references(seed, n_headers)
        trial_rec, trial_problems = run_kv_disk_trial(args, seed, refs)
        trial_rec["problems"] = list(trial_problems)
        record["trials"].append(trial_rec)
        problems.extend(trial_problems)
        print(
            f"kv-disk trial {trial} (seed {seed}): "
            f"seeded_chains={trial_rec['warm']['seeded_chains']} "
            f"warm_p95={trial_rec['warm'].get('ttft_ms_p95')}ms "
            f"cold_p95={trial_rec.get('cold', {}).get('ttft_ms_p95')}ms "
            f"rot_flipped={trial_rec.get('rot', {}).get('flipped_blobs')} "
            f"problems={len(trial_problems)}"
        )

    # ---- hit-rate leg: in-process engines, disk-backed hierarchy vs
    # RAM-only at a working set far above kv_host_blocks (serve_bench
    # owns the workload; loaded by path, same trick as check_daemon)
    spec = importlib.util.spec_from_file_location(
        "serve_bench",
        os.path.join(REPO_ROOT, "scripts", "serve_bench.py"),
    )
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    hit_rec, hit_violations = sb.run_kv_disk_bench(
        None, None, None, seed=args.kv_disk,
        logger=types.SimpleNamespace(log_record=lambda rec: None),
    )
    record["hit_rate_leg"] = hit_rec
    problems.extend(f"hit-rate leg: {v}" for v in hit_violations)

    record["ok"] = not problems
    out = args.record or os.path.join(REPO_ROOT, "KVDISK_r01.json")
    with open(out, "w") as fh:
        json.dump(record, fh, indent=2)
        fh.write("\n")
    print(f"record: {out}")
    return problems


def run_kv_disk_smoke():
    """One reduced warm-restart trial — no TTFT gate (CI boxes are too
    noisy for a latency comparison), no rot leg: spill, kill -9,
    manifest warm-start, typed restores, bitwise replay.  The SSD-tier
    third of the ``check_daemon`` runtime gate."""
    args = argparse.Namespace(
        replicas=1, slots=2, grace=60.0, fsync_batch=4, workdir="",
    )
    seed = 11
    refs = kv_disk_references(seed, n_headers=5)
    _, problems = run_kv_disk_trial(
        args, seed, refs, timing=False, rot_leg=False,
    )
    return problems


def run_soak(args):
    """The seeded kill-9 / restart / drain acceptance soak."""
    from tpu_parallel.daemon import load_state

    record = {"bench": "daemon_soak", "trials": []}
    problems = []
    refs_cache = {}
    for trial in range(args.trials):
        seed = args.seed + trial
        rnd = random.Random(seed ^ 0xD43)
        tmpdir = os.path.join(
            args.workdir or "/tmp", f"daemon_soak_{os.getpid()}_{seed}"
        )
        os.makedirs(tmpdir, exist_ok=True)
        journal = os.path.join(tmpdir, "journal.jsonl")
        ready = os.path.join(tmpdir, "ready.json")
        if os.path.exists(journal):
            os.remove(journal)
        schedule = make_schedule(seed, args.requests, args.new)
        if seed not in refs_cache:
            refs_cache[seed] = greedy_references(schedule)
        refs = refs_cache[seed]
        trial_problems = []

        # ---- life 1: accept traffic, SIGKILL at a seeded point
        proc = spawn_daemon(args, journal, ready)
        info = wait_ready(ready, proc)
        port = info["port"]
        kill_after = rnd.randrange(2, max(3, args.requests - 2))
        accepted = {}
        killed = False
        for i, entry in enumerate(schedule):
            try:
                code, rec = http_json(
                    "POST", f"http://127.0.0.1:{port}/v1/submit", entry
                )
            except (urllib.error.URLError, OSError):
                break  # the daemon is gone (we killed it)
            if code == 200:
                accepted[entry["dedupe_token"]] = rec["request_id"]
            else:
                trial_problems.append(
                    f"life1 submit {i} rejected {code}: {rec}"
                )
            if i + 1 == kill_after:
                # let some tokens stream so the kill lands mid-request
                time.sleep(rnd.uniform(0.2, 0.6))
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)
                killed = True
                break
        if not killed:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        durable = load_state(journal)
        life1 = {
            "accepted": len(accepted),
            "kill_after": kill_after,
            "durable_submits": len(durable.order),
            "durable_unfinished": len(durable.unfinished),
            "torn_records": durable.torn_records,
        }
        if len(durable.order) < len(accepted):
            trial_problems.append(
                f"life1: {len(accepted)} accepts acknowledged but only "
                f"{len(durable.order)} journaled — the WAL lied"
            )

        # ---- life 2: restart on the same journal, idempotent retries
        proc = spawn_daemon(args, journal, ready)
        info = wait_ready(ready, proc)
        port = info["port"]
        dedupe_hits = 0
        all_rids = {}
        for entry in schedule:
            code, rec = http_json(
                "POST", f"http://127.0.0.1:{port}/v1/submit", entry
            )
            if code != 200:
                trial_problems.append(
                    f"life2 submit rejected {code}: {rec}"
                )
                continue
            tok = entry["dedupe_token"]
            all_rids[tok] = rec["request_id"]
            if tok in accepted:
                if rec["request_id"] != accepted[tok]:
                    trial_problems.append(
                        f"life2: dedupe {tok} re-admitted as "
                        f"{rec['request_id']} != {accepted[tok]}"
                    )
                else:
                    dedupe_hits += 1
        deadline = time.monotonic() + 240
        finished = {}
        pending = dict(all_rids)
        while pending and time.monotonic() < deadline:
            for tok, rid in list(pending.items()):
                code, rec = http_json(
                    "GET", f"http://127.0.0.1:{port}/v1/result/{rid}"
                )
                if code == 200 and rec["status"] in (
                    "finished", "failed", "cancelled", "rejected",
                    "expired",
                ):
                    finished[tok] = rec
                    del pending[tok]
            time.sleep(0.05)
        for tok, rid in pending.items():
            trial_problems.append(f"{tok} ({rid}): never terminal")

        # ---- invariants
        for tok, rec in finished.items():
            if rec["status"] != "finished":
                trial_problems.append(
                    f"{tok}: status {rec['status']} "
                    f"({rec['finish_reason']}) — lost accepted work"
                )
                continue
            if rec["tokens"] != refs[tok]:
                trial_problems.append(
                    f"{tok}: tokens diverge from the greedy reference "
                    "through crash+replay"
                )
        state_leak_check(port, trial_problems, f"trial{trial}")
        stop_gracefully(
            proc, args.grace, trial_problems, f"trial{trial}"
        )
        state = journal_invariants(journal, trial_problems)
        trial_rec = {
            "seed": seed,
            "life1": life1,
            "dedupe_hits_on_retry": dedupe_hits,
            "recoveries": state.recoveries,
            "journal_records": state.next_seq,
            "finished": sum(
                1 for r in finished.values()
                if r["status"] == "finished"
            ),
            "requests": args.requests,
            "problems": list(trial_problems),
        }
        record["trials"].append(trial_rec)
        problems.extend(trial_problems)
        if not trial_problems:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
        print(
            f"trial {trial} (seed {seed}): accepted={len(accepted)} "
            f"kill_after={kill_after} dedupe_hits={dedupe_hits} "
            f"finished={trial_rec['finished']}/{args.requests} "
            f"problems={len(trial_problems)}"
        )
    caught = sum(
        t["life1"]["durable_unfinished"] for t in record["trials"]
    )
    if caught == 0:
        problems.append(
            "no trial caught accepted-but-unfinished work at the kill "
            "point — the soak proved nothing about recovery; lengthen "
            "--new or add trials"
        )
    record["unfinished_at_kill_total"] = caught
    record["ok"] = not problems
    if args.record:
        with open(args.record, "w") as fh:
            json.dump(record, fh, indent=2)
            fh.write("\n")
        print(f"record: {args.record}")
    return problems


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true",
                    help="INTERNAL: run the daemon child process")
    ap.add_argument("--smoke", action="store_true",
                    help="fast gate: start, submit, SIGTERM drain, "
                         "assert clean exit (no kill -9)")
    ap.add_argument("--disk-smoke", action="store_true",
                    help="fast integrity gate: one reduced disk-fault "
                         "trial (kill + seeded tail bit flip + bitwise "
                         "recovery), no degraded leg")
    ap.add_argument("--disk-faults", type=int, default=None,
                    metavar="SEED",
                    help="seeded media-corruption soak: kill-torn "
                         "tails, one-bit journal rot, persistent "
                         "fsync-EIO degraded mode — trials use seeds "
                         "SEED..SEED+trials-1")
    ap.add_argument("--kv-disk", type=int, default=None, metavar="SEED",
                    help="SSD-KV-tier acceptance bench: warm vs cold "
                         "restart TTFT on the same disk, seeded blob "
                         "rot, and the serve_bench hit-rate leg; "
                         "writes KVDISK_r01.json by default")
    ap.add_argument("--kv-disk-smoke", action="store_true",
                    help="fast SSD-tier gate: one reduced warm-restart "
                         "trial (spill, kill -9, manifest warm-start, "
                         "typed restores, bitwise replay)")
    ap.add_argument("--kv-disk-dir", type=str, default="",
                    help="INTERNAL (--serve): attach the radix + host "
                         "+ SSD KV hierarchy, one subdirectory per "
                         "replica")
    ap.add_argument("--io-fsync-eio", type=int, default=-1,
                    help="INTERNAL (--serve): arm the IO fault shim "
                         "with persistent fsync EIO from this fsync "
                         "index on")
    ap.add_argument("--soak", action="store_true",
                    help="seeded kill-9/restart soak (the default)")
    ap.add_argument("--journal", type=str, default="")
    ap.add_argument("--ready-file", type=str, default="")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--grace", type=float, default=60.0)
    ap.add_argument("--fsync-batch", type=int, default=8)
    ap.add_argument("--requests", type=int, default=10)
    ap.add_argument("--new", type=int, default=SOAK_NEW_TOKENS)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workdir", type=str, default="")
    ap.add_argument("--record", type=str, default="")
    args = ap.parse_args()

    if args.serve:
        if not args.journal or not args.ready_file:
            ap.error("--serve needs --journal and --ready-file")
        sys.exit(serve(args))
    if args.smoke:
        problems = run_smoke()
    elif args.disk_smoke:
        problems = run_disk_smoke()
    elif args.kv_disk_smoke:
        problems = run_kv_disk_smoke()
    elif args.kv_disk is not None:
        problems = run_kv_disk_soak(args)
    elif args.disk_faults is not None:
        problems = run_disk_soak(args)
    else:
        problems = run_soak(args)
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(
            f"daemon_bench: {len(problems)} INVARIANT VIOLATION(S)",
            file=sys.stderr,
        )
        sys.exit(1)
    print("daemon_bench: OK")


if __name__ == "__main__":
    main()
