"""Shared transformer building blocks, parallelism-aware.

No reference capability exists for any of this (the reference's models are
2-layer MLPs — SURVEY.md §2.4); these layers serve the BASELINE.json
transformer configs (GPT-2 125M/350M, Llama-style 1B).  TPU-first choices:

- bf16 activations / fp32 params and fp32 LayerNorm+softmax accumulation
  (MXU-friendly, numerically safe).
- Tensor parallelism is *structural*, not conditional: attention and MLP
  projections are :class:`~tpu_parallel.parallel.tp.TPDense` over the
  ``model`` axis.  On a mesh where that axis has size 1 the collectives are
  identity — one model definition serves every mesh shape.
- ``nn.remat`` + ``nn.scan`` over layers keep compile time and HBM in check
  at 125M+ scale.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from tpu_parallel.parallel import fsdp
from tpu_parallel.parallel.tp import TPDense, axis_size_or_none


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Architecture + parallelism knobs for the transformer family."""

    vocab_size: int = 50304  # GPT-2's 50257 padded up to a multiple of 128 (MXU lanes)
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    # grouped-query attention: number of K/V heads (None = MHA; 1 = MQA).
    # Q heads are grouped onto the K/V heads after RoPE — natively (no K/V
    # expansion) on the flash and decode paths, by repetition elsewhere.
    n_kv_heads: Optional[int] = None
    seq_len: int = 1024
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    # positional encoding: "learned" (GPT-2), "rope" (Llama), or "relative"
    # (T5: no embedding-level positions — a bucketed per-head bias added to
    # the attention scores, shared across the stack's layers; xla attention
    # path only)
    positional: str = "learned"
    rope_theta: float = 10000.0
    # T5 relative-bias shape knobs (used when positional="relative")
    rel_num_buckets: int = 32
    rel_max_distance: int = 128
    # norm: "layernorm" (GPT-2) or "rmsnorm" (Llama)
    norm: str = "layernorm"
    # norm placement: True = pre-norm (GPT/Llama/T5: x + f(norm(x)), final
    # norm after the stack); False = post-norm (original BERT:
    # norm(x + f(x)), embedding-sum norm instead of a final norm — set
    # embed_norm=True to match).  Post-norm exists for checkpoint interop
    # (models/hf.py BERT import); pre-norm remains the default for
    # from-scratch training (stabler at depth).
    prenorm: bool = True
    # LayerNorm over the embedding sum (token + positional) before the
    # stack — the BERT embeddings.LayerNorm
    embed_norm: bool = False
    # canonical GPT-2/Llama epsilon (flax's default is 1e-6; 1e-5 matches
    # the reference implementations bit-for-bit — models/hf.py interop)
    norm_eps: float = 1e-5
    # mlp: "gelu" (GPT-2's tanh approximation), "gelu_exact" (BERT's erf
    # form — interop-exact against torch), "relu" (original T5), "swiglu"
    # (Llama), or "geglu" (T5 v1.1: gelu-gated, two up projections)
    mlp: str = "gelu"
    # biases on the attention/MLP projections (False for Llama-style and T5
    # checkpoints, True for GPT-2/BERT)
    dense_bias: bool = True
    # parallelism
    model_axis: str = "model"
    data_axis: str = "data"
    pipe_axis: str = "pipe"
    seq_axis: str = "seq"
    num_microbatches: int = 4  # pipeline schedule depth (used when pipe > 1)
    remat: bool = True
    # remat granularity: "full" recomputes everything in the backward pass;
    # "proj" saves only the named projection outputs (qkv/out/up/down) so the
    # backward recomputes just norms, elementwise ops, and attention probs —
    # most of full-remat's memory win without re-running the big matmuls;
    # "proj_attn" additionally saves the attention context and the flash
    # kernel's logsumexp ("attn" names), so the backward never re-runs the
    # attention forward — the fastest policy with attn_impl="flash" (the
    # saved tensors are O(seq), not O(seq^2));
    # "dots" saves every matmul output (includes O(seq^2) attention scores —
    # only viable at short sequence or small batch)
    remat_policy: str = "full"
    scan_layers: bool = True
    # layers per unrolled step of the layer scan (nn.scan's ``unroll``).
    # Measured verdict (SWEEP_r04.json): at 125M the ~11% scan cost persists
    # unchanged under plain remat (not a remat-policy interaction) AND
    # in-scan unrolling makes it WORSE (0.389 MFU at unroll=1 vs
    # 0.349/0.343/0.334 at 2/4/6) — the cost is the per-tick carry
    # round-trips, which unrolling the loop body does not remove.  Deep
    # configs should keep scan_unroll=1 and accept the scan tax, or go
    # fully unrolled (scan_layers=False) where compile budget allows; the
    # knob stays for measurement on other shapes/hardware.
    scan_unroll: int = 1
    # blocks per scanned BODY (scan length becomes n_layers / scan_group):
    # the residual-stream carry is materialized at tick boundaries only, so
    # grouping divides the scan's per-tick HBM round-trips by the group size
    # — unlike scan_unroll, which unrolls the loop but keeps one carry
    # round-trip per block.  Param layout changes to [n_layers/g] stacks of
    # g named blocks ("block0".."block{g-1}"); g=1 keeps the historical
    # layout.  Must divide n_layers.  Measured round 5 (SWEEP_r05.json):
    # FLAT at 125M (0.3876/0.3865/0.3859/0.3867/0.384 MFU at g=1/2/3/4/6)
    # — which falsified the carry-round-trip theory of the scan tax; the
    # bisect then located it in the backward (fwd +6.6%, bwd +15.7% vs
    # unrolled).  The knob stays for other depths/hardware.
    scan_group: int = 1
    # lax.scan's _split_transpose: lowers the layer scan's BACKWARD as two
    # loops (residual regeneration + gradient accumulation) that XLA can
    # overlap.  The measured scan tax lives in the backward (fwd +6.6%,
    # bwd +15.7% vs unrolled at 125M/batch16 — round-5 bisect), which is
    # exactly the pass this targets.
    scan_split_transpose: bool = False
    fsdp: bool = False  # shard big params over the data axis (ZeRO-3)
    fsdp_min_size: int = 2**18
    attn_impl: str = "xla"  # "xla" | "flash" | "ring" | "ulysses"
    # flash kernel tile sizes; 512x512 measured fastest on v5e at seq 1024
    # (scripts/attn_microbench.py: 10.5ms vs 17.2ms fwd+bwd at 128x128)
    flash_block_q: int = 512
    flash_block_k: int = 512
    # sliding-window attention: 0 = full causal; >0 = each query sees only
    # the last `attn_window` positions (Mistral-style).  Applies to every
    # attention impl: xla, flash (whole out-of-window key blocks skipped
    # in-kernel), ring (out-of-window chunks skip their kernels entirely),
    # ulysses (band applied on the gathered sequence), and decode.
    attn_window: int = 0
    # decode KV-cache storage: "bf16" (= cfg.dtype) or "int8" — int8 halves
    # the cache HBM (the decode-memory hog) with one fp32 scale per
    # (position, kv-head); the attention read is int8-NATIVE (scales fold
    # into the score/value matmuls inside decode_attention — no
    # dequantized cache copy), except the lazy-beam path which still
    # dequantizes transiently per layer per step
    kv_cache_dtype: str = "bf16"
    # paged decode KV cache (the serving engine's block-table layout):
    # kv_block_tokens > 0 stores decode K/V in a flat pool of
    # ``kv_pool_blocks`` fixed-size blocks of ``kv_block_tokens`` positions
    # each instead of per-row ``seq_len`` stripes.  Every decode call must
    # then pass ``block_table`` [batch, seq_len // kv_block_tokens] mapping
    # each row's logical block index to a physical pool block (-1 =
    # unmapped: reads masked out, writes dropped) plus ``write_index`` —
    # the engine owns the tables through
    # :class:`~tpu_parallel.serving.cache_pool.BlockAllocator`.  0 = the
    # classic contiguous per-row cache.  Set ONLY by the serving engine
    # (it rebuilds its model with these fields); training and the static
    # generate() paths never page.
    kv_block_tokens: int = 0
    kv_pool_blocks: int = 0
    # lazy beam-search decode: >1 switches the decode attention to the
    # cross-beam form (beam j of prompt i = row i*k+j) that follows beam
    # ancestry through a per-slot source-row table instead of physically
    # re-gathering every layer's KV cache every step.  Set ONLY by the beam
    # loops (models/generate.py builds a beam_width=k model for the decode
    # scan); 0 everywhere else.
    beam_width: int = 0
    # bidirectional (encoder / BERT-style) attention: every position sees
    # every same-segment position — with attn_window > 0, those in the
    # symmetric band |q - k| < window (encoder local attention).  Composes
    # with the xla and flash paths, GQA, packing, TP/FSDP/PP, ulysses SP
    # (band applied on the gathered sequence), and ring SP (the band spans
    # chunks via signed static offsets; out-of-band chunks skip their
    # kernels); refuses decode (encoders don't autoregress)
    bidirectional: bool = False
    # mixture-of-experts: 0 = dense MLP; >0 replaces every block's MLP with
    # routed experts, expert-parallel over the model axis
    moe_experts: int = 0
    # routing family: "topk" (tokens choose experts; see moe_top_k) or
    # "expert_choice" (experts choose their top-capacity tokens — perfectly
    # balanced by construction, no aux loss; NOT causal: a token's routing
    # depends on the whole batch, including later positions, so use for
    # encoders/non-AR objectives or accept the leak knowingly)
    moe_router: str = "topk"
    # experts per token: 1 = Switch (gate = router prob), >1 = GShard-style
    # (gates renormalized over the chosen experts)
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_balance_weight: float = 0.01
    # EP dispatch mechanics: "dense" replicates the token set over the EP
    # ranks and builds [T, E, C] one-hot dispatch/combine masks (zero
    # communication on dispatch, one psum on combine — fine on small
    # meshes, but per-rank mask memory and dispatch-einsum cost grow with
    # the FULL token count).  "alltoall" shards the token set over the EP
    # axis: each rank routes its T/ep tokens locally ([T/ep, E, C/ep]
    # masks — ep^2 smaller), exchanges expert payloads with one
    # all_to_all each way, and closes with an all_gather of the combined
    # tokens.  Capacity becomes a per-(sender, expert) quota of C/ep
    # (GShard's formulation): identical results while nothing overflows,
    # different drop choices under pressure.  topk router only
    # (expert_choice needs global top-capacity; it stays dense).
    moe_dispatch: str = "dense"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def seq_parallel_active(config: TransformerConfig) -> bool:
    """True when attention shards the token axis: a seq-parallel impl is
    selected AND the seq mesh axis is actually bound (shard_map region)."""
    return config.attn_impl in ("ring", "ulysses") and bool(
        axis_size_or_none(config.seq_axis)
    )


def make_norm(config: TransformerConfig, name: str):
    """fp32 norm (LayerNorm or RMSNorm) — small, precision-critical."""
    if config.norm == "rmsnorm":
        return nn.RMSNorm(epsilon=config.norm_eps, dtype=jnp.float32, name=name)
    return nn.LayerNorm(epsilon=config.norm_eps, dtype=jnp.float32, name=name)


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float = 10000.0
) -> jax.Array:
    """Rotary position embedding over the last (head_dim) axis.

    ``x``: [batch, seq, heads, head_dim]; ``positions``: [batch, seq].
    """
    head_dim = x.shape[-1]
    freq_exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**freq_exponents)  # [head_dim/2]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, S, hd/2]
    angles = angles[:, :, None, :]  # broadcast over heads
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., ::2], x[..., 1::2]
    rotated = jnp.stack(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).reshape(x.shape)
    return rotated.astype(x.dtype)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: Optional[jax.Array] = None,
    window: int = 0,
    causal: bool = True,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference attention: fp32 softmax, bf16 matmuls on the MXU.

    ``q, k, v``: [batch, seq, heads, head_dim].  O(seq^2) memory — the
    Pallas flash kernel (``ops.flash_attention``) replaces this on TPU for
    long sequences.  ``causal=False`` is the bidirectional (encoder) form:
    every position attends every (same-segment) position — with ``window``,
    those within the symmetric band |q - k| < window.
    """
    head_dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(head_dim).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    scores = scores.astype(jnp.float32)
    if bias is not None:
        # additive position bias [1|B, h, q, k] (T5 relative bias)
        scores = scores + bias.astype(jnp.float32)
    q_pos = lax.broadcasted_iota(jnp.int32, scores.shape, 2)
    k_pos = lax.broadcasted_iota(jnp.int32, scores.shape, 3)
    mask = q_pos >= k_pos if causal else None
    if window:
        # causal: query t attends keys in (t - window, t]; bidirectional
        # (encoder local attention): the symmetric band |q - k| < window
        near = q_pos - k_pos < window
        if not causal:
            near = jnp.logical_and(near, k_pos - q_pos < window)
        mask = near if mask is None else jnp.logical_and(mask, near)
    if segment_ids is not None:
        same_seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = same_seg if mask is None else jnp.logical_and(mask, same_seg)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def decode_attention(
    q: jax.Array, k_all: jax.Array, v_all: jax.Array, positions: jax.Array,
    window: int = 0, bias: Optional[jax.Array] = None,
    k_positions: Optional[jax.Array] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention of new queries against a full KV cache, GQA-native.

    ``q``: [batch, new_len, heads, head_dim] at global ``positions``
    [batch, new_len]; ``k_all``/``v_all``: [batch, cache_len, kv_heads,
    head_dim] where ``heads % kv_heads == 0`` (grouped queries contract
    against their group's K/V directly — no repeated-K/V materialization).

    ``k_positions``: the global position each cache slot holds.  Default
    (None) is the aligned layout — slot j holds position j, entries beyond
    the write index masked out by the position comparison.  Ragged batches
    (left-padded prompts) pass the per-row table ``[batch, cache_len]``
    where pad slots hold -1: negative slots never attend, and the causal
    comparison keys off the STORED positions, not slot indices.

    ``k_scale``/``v_scale`` [batch, cache_len, kv_heads, 1] switch to the
    INT8-NATIVE read: ``k_all``/``v_all`` are the raw int8 payloads and
    the per-(position, kv-head) scales fold into the surrounding matmuls
    — K scales multiply the scores AFTER the q·k contraction (a scale is
    constant over head_dim, so ``q·(kq*ks) == (q·kq)*ks`` exactly), and
    V scales fold into the probability weights (``(w*vs)·vq``).  The int8
    payload feeds the dot directly (the int8→compute-dtype cast is
    elementwise, fused into the dot's operand read); no dequantized
    cache-sized copy is ever materialized — the transient bf16 K+V copies
    per layer per step were the whole int8 decode cliff (DECODE_r06:
    9.8k vs 22.6k tok/s at batch 32).
    """
    b, nq, h, head_dim = q.shape
    h_kv = k_all.shape[2]
    group = h // h_kv
    scale = 1.0 / jnp.sqrt(head_dim).astype(q.dtype)
    qg = (q * scale).reshape(b, nq, h_kv, group, head_dim)
    k_in = k_all if k_scale is None else k_all.astype(q.dtype)
    scores = jnp.einsum("bqngd,bknd->bngqk", qg, k_in).astype(jnp.float32)
    if k_scale is not None:
        # fold K scales post-matmul: [b, S, n, 1] -> [b, n, 1, 1, S]
        ks = k_scale[..., 0].transpose(0, 2, 1)[:, :, None, None, :]
        scores = scores * ks
    if bias is not None:
        # [1|B, h, q, k] -> grouped [1|B, h_kv, group, q, k]
        bb = bias.reshape(bias.shape[0], h_kv, group, *bias.shape[2:])
        scores = scores + bb.astype(jnp.float32)
    if k_positions is None:
        k_pos = jnp.broadcast_to(jnp.arange(k_all.shape[1]), (b, k_all.shape[1]))
    else:
        k_pos = k_positions
    kp = k_pos[:, None, None, None, :]
    qp = positions[:, None, None, :, None]
    mask = jnp.logical_and(kp >= 0, kp <= qp)
    if window:
        mask = jnp.logical_and(mask, qp - kp < window)
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if v_scale is None:
        out = jnp.einsum("bngqk,bknd->bqngd", probs, v_all)
    else:
        # fold V scales into the probability weights (fp32 multiply, one
        # round back to the compute dtype) so the int8 V payload feeds
        # the value contraction directly
        vs = v_scale[..., 0].transpose(0, 2, 1)[:, :, None, None, :]
        w = (probs.astype(jnp.float32) * vs).astype(q.dtype)
        out = jnp.einsum("bngqk,bknd->bqngd", w, v_all.astype(q.dtype))
    return out.reshape(b, nq, h, head_dim)


def beam_decode_attention(
    q: jax.Array, k_all: jax.Array, v_all: jax.Array, positions: jax.Array,
    beam_src: jax.Array, num_beams: int, window: int = 0,
    bias: Optional[jax.Array] = None,
    k_positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode attention against an UN-reordered beam-search KV cache.

    Rows are beam-major: beam j of prompt i is row ``i*num_beams + j``.
    ``beam_src`` [rows, cache_len] names, per row and cache slot, the row
    (within the same prompt's beam group) whose cache physically holds that
    slot of this beam's history — the beam loop maintains it (each written
    slot maps to the writing row; a row-gather by winner parents follows
    every top-k).  Mathematically identical to physically gathering cache
    rows by beam ancestry, but the cache is read once and never rewritten:
    scores/values are computed all-pairs over the ``num_beams`` group rows
    (k x the attention FLOPs — noise in bandwidth-bound decode, where the
    eager reorder's full cache read+write per layer per step dominates)
    and the right pair is selected per slot from the table.
    """
    rows, nq, h, head_dim = q.shape
    kb = num_beams
    b = rows // kb
    if b * kb != rows:
        raise ValueError(f"rows={rows} not divisible by num_beams={kb}")
    cache_len = k_all.shape[1]
    h_kv = k_all.shape[2]
    group = h // h_kv
    scale = 1.0 / jnp.sqrt(head_dim).astype(q.dtype)
    qg = (q * scale).reshape(b, kb, nq, h_kv, group, head_dim)
    kg = k_all.reshape(b, kb, cache_len, h_kv, head_dim)
    # all-pairs scores over the beam group: [b, j, j', h_kv, group, q, slot]
    scores = jnp.einsum("bjqngd,bpsnd->bjpngqs", qg, kg).astype(jnp.float32)
    # per (row, slot) select the source beam's score
    src_local = (beam_src.reshape(b, kb, cache_len) % kb).astype(jnp.int32)
    idx = src_local[:, :, None, None, None, None, :]  # [b, j, 1, 1, 1, 1, s]
    sel = jnp.take_along_axis(scores, idx, axis=2)[:, :, 0]  # [b,j,n,g,q,s]
    sel = sel.reshape(rows, h_kv, group, nq, cache_len)
    if bias is not None:
        bb = bias.reshape(bias.shape[0], h_kv, group, *bias.shape[2:])
        sel = sel + bb.astype(jnp.float32)
    if k_positions is None:
        k_pos = jnp.broadcast_to(jnp.arange(cache_len), (rows, cache_len))
    else:
        k_pos = k_positions
    kp = k_pos[:, None, None, None, :]
    qp = positions[:, None, None, :, None]
    mask = jnp.logical_and(kp >= 0, kp <= qp)
    if window:
        mask = jnp.logical_and(mask, qp - kp < window)
    sel = jnp.where(mask, sel, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(sel, axis=-1).astype(q.dtype)
    # value side: bucket each row's probs by source beam (one-hot over j')
    # and contract all-pairs — V is read once, never gathered
    pg = probs.reshape(b, kb, h_kv, group, nq, cache_len)
    onehot = jax.nn.one_hot(src_local, kb, axis=2, dtype=q.dtype)
    # onehot: [b, j, j', s]; pm: [b, j, j', n, g, q, s]
    pm = pg[:, :, None] * onehot[:, :, :, None, None, None, :]
    vg = v_all.reshape(b, kb, cache_len, h_kv, head_dim)
    out = jnp.einsum("bjpngqs,bpsnd->bjqngd", pm, vg)
    return out.reshape(rows, nq, h, head_dim)


def t5_relative_bucket(
    relative_position: jax.Array,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """T5's relative-position bucketing (log-spaced beyond ``max_exact``).

    ``relative_position`` is ``k_pos - q_pos``.  Bidirectional stacks split
    the buckets between past and future; causal stacks bucket only the past
    (future positions land in bucket 0 and are masked out by the causal
    mask anyway).  Mirrors ``_relative_position_bucket`` in the canonical
    implementation so imported tables index identically.
    """
    rp = relative_position
    bucket = jnp.zeros_like(rp)
    if bidirectional:
        num_buckets = num_buckets // 2
        bucket = bucket + (rp > 0).astype(jnp.int32) * num_buckets
        rp = jnp.abs(rp)
    else:
        rp = -jnp.minimum(rp, 0)
    max_exact = num_buckets // 2
    is_small = rp < max_exact
    scaled = max_exact + (
        jnp.log(jnp.maximum(rp, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    scaled = jnp.minimum(scaled, num_buckets - 1)
    return bucket + jnp.where(is_small, rp, scaled)


class RelativePositionBias(nn.Module):
    """T5-style bucketed per-head position bias, shared across a stack.

    ``(q_positions [Q], k_positions [K]) -> bias [1, n_heads, Q, K]``
    (fp32).  The bucket table is a tiny replicated param
    ``[num_buckets, n_heads]``; under TP the caller's Attention slices its
    local heads off the full-width bias.
    """

    config: TransformerConfig
    bidirectional: bool

    @nn.compact
    def __call__(self, q_positions: jax.Array, k_positions: jax.Array):
        cfg = self.config
        rel = k_positions[None, :] - q_positions[:, None]  # [Q, K]
        bucket = t5_relative_bucket(
            rel, self.bidirectional, cfg.rel_num_buckets, cfg.rel_max_distance
        )
        table = self.param(
            "rel_embedding",
            nn.initializers.normal(stddev=1.0),
            (cfg.rel_num_buckets, cfg.n_heads),
        )
        bias = jnp.asarray(table, jnp.float32)[bucket]  # [Q, K, H]
        return bias.transpose(2, 0, 1)[None]  # [1, H, Q, K]

    def for_step(
        self,
        positions: Optional[jax.Array],
        q_len: int,
        cache_len: int,
        decode: bool,
    ) -> jax.Array:
        """The positions-to-bias recipe shared by GPTLM and the seq2seq
        decoder: queries at ``positions`` (row 0 — every current caller
        broadcasts uniform positions; packed/ragged rows are refused
        upstream) against themselves (training) or every cache slot
        (``decode``)."""
        q_pos = positions[0] if positions is not None else jnp.arange(q_len)
        k_pos = jnp.arange(cache_len) if decode else q_pos
        return self(q_pos, k_pos)


def bidirectional_flash_attention(q, k, v, segment_ids=None, *, block_q,
                                  block_k, window=0):
    """Full-visibility flash attention: ONE non-causal "chunk" spanning the
    whole sequence (native GQA + in-kernel segment masking; lse discarded).
    ``window`` restricts to the symmetric band |q - k| < window (encoder
    local attention) with out-of-band key blocks skipped in-kernel.
    Shared by the encoder's flash path and its Ulysses inner attention."""
    from tpu_parallel.ops.flash_attention import flash_chunk_attention

    out, _ = flash_chunk_attention(
        q, k, v, causal=False, block_q=block_q, block_k=block_k, window=window,
        segment_ids_q=segment_ids, segment_ids_kv=segment_ids,
    )
    return out


class Attention(nn.Module):
    """Multi-head causal self-attention, heads sharded over the model axis.

    QKV is one fused column-parallel projection (each model rank owns
    ``n_heads / tp`` heads); the output projection is row-parallel, closing
    the Megatron f/g pair with a single psum.

    ``decode=True`` switches to incremental decoding: K/V are appended to a
    ``cache`` collection of length ``seq_len`` (created on first mutable
    apply), and queries attend to the full cache prefix.  The same path
    serves prefill (multi-token write at index 0) and per-token decode.

    ``write_index`` [batch] enables SLOT-INDEXED cache writes for the
    continuous-batching engine (``tpu_parallel.serving``): each row's
    K/V lands at its OWN cache slots (``write_index + [0..tokens)``)
    instead of the shared scalar ``cache_index`` — rows in the same step
    may sit at different depths of their generations, and a multi-token
    step extends a row's cache by one prompt chunk (the engine's chunked
    prefill).  A row whose ``write_index`` is parked at ``seq_len``
    drops its ENTIRE multi-token write (every target out of range /
    unmapped — the scatter-discard contract), which is what lets the
    engine's unified ragged tick run one fixed-shape chunk pass over
    the whole slot pool with only the prefilling rows landing writes.
    The attention read is unchanged (it already keys off the stored
    per-slot position table, not slot indices), so aligned and
    slot-indexed layouts read identically.
    """

    config: TransformerConfig
    # injected attention implementation; defaults resolved in __call__
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        cache_valid: Optional[jax.Array] = None,
        attn_bias: Optional[jax.Array] = None,
        write_index: Optional[jax.Array] = None,
        block_table: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        tp_size = axis_size_or_none(cfg.model_axis) or 1
        if attn_bias is not None and tp_size > 1:
            # the model-level bias covers all heads; keep this rank's slice
            lh = attn_bias.shape[1] // tp_size
            attn_bias = lax.dynamic_slice_in_dim(
                attn_bias, lax.axis_index(cfg.model_axis) * lh, lh, axis=1
            )
        n_kv = cfg.n_kv_heads or cfg.n_heads
        if cfg.n_heads % tp_size != 0:
            raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp_size}")
        if n_kv % tp_size != 0 or cfg.n_heads % n_kv != 0:
            raise ValueError(
                f"n_kv_heads={n_kv} must divide n_heads={cfg.n_heads} and be "
                f"divisible by tp={tp_size}"
            )
        local_heads = cfg.n_heads // tp_size
        local_kv = n_kv // tp_size
        if cfg.bidirectional:
            if decode:
                raise NotImplementedError(
                    "incremental decoding with bidirectional attention "
                    "(encoders do not autoregress)"
                )
        if n_kv == cfg.n_heads:
            qkv = TPDense(
                features=3 * cfg.d_model,
                axis_name=cfg.model_axis,
                style="column",
                use_bias=cfg.dense_bias,
                dtype=cfg.dtype,
                name="qkv",
            )(x)
            qkv = checkpoint_name(qkv, "proj")
            qkv = qkv.reshape(*x.shape[:-1], local_heads, 3 * cfg.head_dim)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            # GQA: separate projections (Q is n_heads wide, KV n_kv wide)
            q = TPDense(
                features=cfg.n_heads * cfg.head_dim,
                axis_name=cfg.model_axis,
                style="column",
                use_bias=cfg.dense_bias,
                dtype=cfg.dtype,
                name="q",
            )(x)
            q = checkpoint_name(q, "proj").reshape(
                *x.shape[:-1], local_heads, cfg.head_dim
            )
            kv = TPDense(
                features=2 * n_kv * cfg.head_dim,
                axis_name=cfg.model_axis,
                style="column",
                use_bias=cfg.dense_bias,
                dtype=cfg.dtype,
                name="kv",
            )(x)
            kv = checkpoint_name(kv, "proj").reshape(
                *x.shape[:-1], local_kv, 2 * cfg.head_dim
            )
            k, v = jnp.split(kv, 2, axis=-1)
        if decode:
            if seq_parallel_active(cfg):
                raise NotImplementedError(
                    "incremental decoding under sequence parallelism"
                )
            if segment_ids is not None:
                raise NotImplementedError(
                    "incremental decoding with packed sequences (segment_ids)"
                )
            b = x.shape[0]
            if cfg.kv_cache_dtype not in ("bf16", "int8"):
                raise ValueError(
                    f"kv_cache_dtype={cfg.kv_cache_dtype!r} (bf16 | int8)"
                )
            quant_cache = cfg.kv_cache_dtype == "int8"
            cache_store_dtype = jnp.int8 if quant_cache else cfg.dtype
            paged = cfg.kv_block_tokens > 0
            if paged:
                # block-paged layout: K/V live in a FLAT pool of
                # kv_pool_blocks blocks of kv_block_tokens positions each,
                # shared by every row; rows address it through their
                # block_table entries.  The pool is row-count-free — slot
                # capacity decouples from seq_len.
                if cfg.kv_pool_blocks < 1:
                    raise ValueError(
                        f"kv_block_tokens={cfg.kv_block_tokens} needs "
                        f"kv_pool_blocks >= 1 (got {cfg.kv_pool_blocks})"
                    )
                if block_table is None or write_index is None:
                    raise ValueError(
                        "paged KV cache (kv_block_tokens > 0) requires "
                        "block_table AND write_index — the serving "
                        "engine's block-allocator path is the only caller"
                    )
                if cfg.beam_width > 1:
                    raise NotImplementedError(
                        "paged KV cache under lazy beam search (beam_src "
                        "bookkeeping assumes contiguous per-row caches)"
                    )
                kv_store = (
                    cfg.kv_pool_blocks, cfg.kv_block_tokens, local_kv,
                    cfg.head_dim,
                )
                scale_store = (
                    cfg.kv_pool_blocks, cfg.kv_block_tokens, local_kv, 1
                )
                pos_store = (cfg.kv_pool_blocks, cfg.kv_block_tokens)
            else:
                kv_store = (b, cfg.seq_len, local_kv, cfg.head_dim)
                scale_store = (b, cfg.seq_len, local_kv, 1)
                pos_store = (b, cfg.seq_len)
            # cache at K/V-head width (local_kv): under GQA this is the whole
            # point — n_heads/n_kv less cache HBM; decode_attention contracts
            # grouped queries against it directly (no expansion)
            cached_k = self.variable(
                "cache",
                "cached_key",
                jnp.zeros,
                kv_store,
                cache_store_dtype,
            )
            cached_v = self.variable(
                "cache",
                "cached_value",
                jnp.zeros,
                kv_store,
                cache_store_dtype,
            )
            if quant_cache:
                # one fp32 scale per (position, kv-head): int8 payload + a
                # head_dim-th of fp32 ≈ half the bf16 cache HBM
                cached_k_scale = self.variable(
                    "cache",
                    "cached_key_scale",
                    jnp.zeros,
                    scale_store,
                    jnp.float32,
                )
                cached_v_scale = self.variable(
                    "cache",
                    "cached_value_scale",
                    jnp.zeros,
                    scale_store,
                    jnp.float32,
                )
            # per-slot global positions (int32) — the decode mask keys off
            # STORED positions, so ragged (left-padded) batches work: pad
            # slots hold -1 and never attend.  Aligned batches write j at
            # slot j, reproducing the classic layout.  Paged mode stores
            # the table per (block, offset); freed blocks are re-invalidated
            # to -1 by the allocator before reuse.
            cached_p = self.variable(
                "cache",
                "cached_pos",
                lambda: jnp.full(pos_store, -1, jnp.int32),
            )
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            idx = cache_index.value
            if positions is None:
                positions = jnp.broadcast_to(
                    idx + jnp.arange(x.shape[1])[None, :], x.shape[:2]
                )
        if cfg.positional == "rope":
            if positions is None:
                local = jnp.arange(x.shape[1])
                if seq_parallel_active(cfg):
                    # seq-sharded: offset local positions to global ones
                    local = local + lax.axis_index(cfg.seq_axis) * x.shape[1]
                positions = jnp.broadcast_to(local, x.shape[:2])
            q = apply_rope(q, positions, cfg.rope_theta)
            k = apply_rope(k, positions, cfg.rope_theta)
        if decode:
            # cache_valid gates persistence (pipeline decode: only the rank
            # whose tick this is may commit writes — other ranks run the
            # same program on garbage activations and must leave their cache
            # untouched).  The attention read uses the fresh buffers either
            # way; invalid ticks' outputs are discarded downstream.
            if cache_valid is None:
                keep = lambda new, old: new
            else:
                keep = lambda new, old: jnp.where(cache_valid, new, old)
            if write_index is not None:
                # per-row slot writes (continuous batching): the update is a
                # batched scatter starting at each row's own index, not one
                # contiguous dynamic-slice.  Multi-token steps write each
                # row's tokens at write_index + [0..T) — the chunked-prefill
                # path (serving engine) extends a slot's cache one prompt
                # chunk at a time between decode ticks.
                if cfg.beam_width > 1:
                    raise NotImplementedError(
                        "write_index under lazy beam search (beam_src slot "
                        "bookkeeping assumes the shared scalar cache_index)"
                    )
                wi = (
                    write_index.astype(jnp.int32)[:, None]
                    + jnp.arange(x.shape[1])[None, :]
                )
                if paged:
                    # logical column -> (physical block, offset) through the
                    # row's block table: table[row, col // bt] * bt +
                    # col % bt.  Unmapped (-1) table entries and logical
                    # blocks beyond the table width redirect to pool index
                    # kv_pool_blocks — out of range, DROPPED by scatter
                    # semantics, the same discard the contiguous layout's
                    # column-seq_len park relies on.
                    bt = cfg.kv_block_tokens
                    lblk = wi // bt
                    ok = lblk < block_table.shape[1]
                    phys = jnp.take_along_axis(
                        block_table, jnp.where(ok, lblk, 0), axis=1
                    )
                    phys = jnp.where(
                        ok & (phys >= 0), phys, cfg.kv_pool_blocks
                    )
                    off = wi % bt
                    upd = lambda buf, new: buf.at[phys, off].set(
                        new.astype(buf.dtype)
                    )
                else:
                    rows = jnp.arange(b)[:, None]
                    # out-of-range targets (a pool's free slots, a padded
                    # chunk's tail beyond seq_len) fall under JAX's default
                    # scatter semantics: the update is DROPPED, leaving the
                    # cache intact — deliberately not clamped, which would
                    # overwrite a valid boundary entry instead
                    upd = lambda buf, new: buf.at[rows, wi].set(
                        new.astype(buf.dtype)
                    )
            else:
                upd = lambda buf, new: lax.dynamic_update_slice_in_dim(
                    buf, new, idx, axis=1
                )
            k_scale = v_scale = None
            if quant_cache:
                from tpu_parallel.models.quantize import absmax_int8

                kq, ks = absmax_int8(k, axis=-1)
                vq, vs = absmax_int8(v, axis=-1)
                new_k = upd(cached_k.value, kq)
                new_v = upd(cached_v.value, vq)
                new_ks = upd(cached_k_scale.value, ks)
                new_vs = upd(cached_v_scale.value, vs)
                cached_k.value = keep(new_k, cached_k.value)
                cached_v.value = keep(new_v, cached_v.value)
                cached_k_scale.value = keep(new_ks, cached_k_scale.value)
                cached_v_scale.value = keep(new_vs, cached_v_scale.value)
                if cfg.beam_width > 1:
                    # the cross-beam all-pairs read has no scale fold yet:
                    # keep the transient dequantized copy on this path only
                    k_all = (
                        new_k.astype(jnp.float32) * new_ks
                    ).astype(cfg.dtype)
                    v_all = (
                        new_v.astype(jnp.float32) * new_vs
                    ).astype(cfg.dtype)
                else:
                    # int8-native read: the payloads go to decode_attention
                    # raw, scales fold into the score/value matmuls — no
                    # dequantized cache copy is materialized
                    k_all, v_all = new_k, new_v
                    k_scale, v_scale = new_ks, new_vs
            else:
                k_all = upd(cached_k.value, k)
                v_all = upd(cached_v.value, v)
                cached_k.value = keep(k_all, cached_k.value)
                cached_v.value = keep(v_all, cached_v.value)
            new_p = upd(cached_p.value, positions.astype(jnp.int32))
            cached_p.value = keep(new_p, cached_p.value)
            cache_index.value = keep(idx + x.shape[1], idx)
            if cfg.beam_width > 1:
                # lazy beam search: the cache rows are never re-gathered;
                # a per-slot source-row table follows beam ancestry instead.
                # This layer's contract: every slot IT writes maps to the
                # writing row (the beam loop row-gathers the table by winner
                # parents after each top-k).
                own_row = jnp.arange(b, dtype=jnp.int32)[:, None]
                beam_src = self.variable(
                    "cache",
                    "beam_src",
                    lambda: own_row + jnp.zeros((b, cfg.seq_len), jnp.int32),
                )
                new_src = lax.dynamic_update_slice_in_dim(
                    beam_src.value,
                    own_row + jnp.zeros((b, x.shape[1]), jnp.int32),
                    idx,
                    axis=1,
                )
                beam_src.value = keep(new_src, beam_src.value)
                out = beam_decode_attention(
                    q, k_all, v_all, positions, new_src, cfg.beam_width,
                    window=cfg.attn_window, bias=attn_bias, k_positions=new_p,
                )
            else:
                k_pos = new_p
                if paged:
                    # assemble each row's LOGICAL K/V view by gathering its
                    # blocks out of the flat pool (one gather per payload;
                    # logical column c = pool[table[c // bt], c % bt]), so
                    # the attention math below is untouched and paged greedy
                    # output is bitwise identical to the contiguous layout
                    bt = cfg.kv_block_tokens
                    tbl = jnp.maximum(block_table, 0)

                    def pages(buf):
                        g = jnp.take(buf, tbl, axis=0)
                        return g.reshape(
                            b, tbl.shape[1] * bt, *buf.shape[2:]
                        )

                    k_all, v_all = pages(k_all), pages(v_all)
                    if k_scale is not None:
                        k_scale, v_scale = pages(k_scale), pages(v_scale)
                    # unmapped (-1) table entries gathered block 0's
                    # contents above — mask them out through the stored
                    # positions (-1 never attends)
                    mapped = jnp.repeat(block_table >= 0, bt, axis=1)
                    k_pos = jnp.where(mapped, pages(new_p), -1)
                # decode_attention contracts grouped queries against the
                # kv-width cache directly — no K/V expansion
                out = decode_attention(
                    q, k_all, v_all, positions, window=cfg.attn_window,
                    bias=attn_bias, k_positions=k_pos,
                    k_scale=k_scale, v_scale=v_scale,
                )
        else:
            out = self._attend(q, k, v, segment_ids, attn_bias)
        if cfg.attn_impl != "flash":
            # let the "proj_attn" remat policy keep the attention context so
            # the backward never recomputes it — an O(seq) residual.  The
            # flash path already names its kernel-layout out+lse inside
            # ops/flash_attention.py; naming this transpose too would save
            # the same tensor twice.
            out = checkpoint_name(out, "attn")
        out = out.reshape(*x.shape[:-1], local_heads * cfg.head_dim)
        out = TPDense(
            features=cfg.d_model,
            axis_name=cfg.model_axis,
            style="row",
            use_bias=cfg.dense_bias,
            dtype=cfg.dtype,
            name="out",
        )(out)
        out = checkpoint_name(out, "proj")
        if cfg.dropout_rate > 0.0:
            out = nn.Dropout(rate=cfg.dropout_rate, deterministic=not train)(out)
        return out

    def _attend(self, q, k, v, segment_ids, attn_bias=None):
        cfg = self.config
        if attn_bias is not None and cfg.attn_impl != "xla":
            # the Pallas/ring/ulysses kernels take no additive score bias;
            # T5-style models must run the xla attention path
            raise NotImplementedError(
                f"attention score bias (positional='relative') under "
                f"attn_impl={cfg.attn_impl!r} — use attn_impl='xla'"
            )
        group = q.shape[-2] // k.shape[-2]
        native_group = (
            cfg.attn_impl in ("flash", "ring", "ulysses")
            and self.attn_fn is None
        )
        if group != 1 and not native_group:
            # GQA head expansion for the paths without native group routing
            # (xla einsum, injected hooks).  XLA fuses this broadcast into
            # the einsum contractions.  The Pallas flash path must NOT take
            # it — kernel operands are materialized buffers, so it routes
            # groups via BlockSpec index maps; ring keeps K/V at kv-head
            # width because THEY ride the ppermute ring (group x less ring
            # traffic; the jnp ring contracts grouped queries natively,
            # like decode_attention); ulysses reshards kv heads at kv width
            # (group x less all_to_all volume) or expands internally when
            # h_kv doesn't divide the axis.
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        attn_fn = self.attn_fn
        if attn_fn is None:
            if cfg.attn_impl == "flash" and cfg.bidirectional:
                attn_fn = functools.partial(
                    bidirectional_flash_attention,
                    block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
                    window=cfg.attn_window,
                )
            elif cfg.attn_impl == "flash":
                from tpu_parallel.ops.flash_attention import flash_attention

                attn_fn = functools.partial(
                    flash_attention,
                    block_q=cfg.flash_block_q,
                    block_k=cfg.flash_block_k,
                    window=cfg.attn_window,
                )
            elif cfg.attn_impl == "ring":
                from tpu_parallel.ops.ring_attention import (
                    ring_attention,
                    ring_flash_attention,
                )

                # flash-composed ring on TPU; the jnp path elsewhere (the
                # interpret-mode kernels can't declare vma for the trainer's
                # replication checker, and CPU gains nothing from them).
                # segment_ids (packed sequences) are the LOCAL chunk's ids —
                # both impls rotate them around the ring with their K/V.
                if jax.default_backend() == "tpu":

                    def attn_fn(q, k, v, segment_ids=None):
                        return ring_flash_attention(
                            q, k, v, axis_name=cfg.seq_axis,
                            block_q=cfg.flash_block_q,
                            block_k=cfg.flash_block_k,
                            window=cfg.attn_window,
                            segment_ids=segment_ids,
                            causal=not cfg.bidirectional,
                        )

                else:

                    def attn_fn(q, k, v, segment_ids=None):
                        return ring_attention(
                            q, k, v, axis_name=cfg.seq_axis,
                            window=cfg.attn_window,
                            segment_ids=segment_ids,
                            causal=not cfg.bidirectional,
                        )

            elif cfg.attn_impl == "ulysses":
                from tpu_parallel.ops.flash_attention import flash_attention
                from tpu_parallel.ops.ulysses import ulysses_attention

                # the inner attention sees the full gathered sequence, so the
                # window band (causal) or full visibility (bidirectional)
                # applies directly
                if cfg.bidirectional:
                    inner = functools.partial(
                        bidirectional_flash_attention,
                        block_q=cfg.flash_block_q,
                        block_k=cfg.flash_block_k,
                        window=cfg.attn_window,
                    )
                else:
                    inner = functools.partial(
                        flash_attention,
                        block_q=cfg.flash_block_q,
                        block_k=cfg.flash_block_k,
                        window=cfg.attn_window,
                    )

                def attn_fn(q, k, v, segment_ids=None):
                    if segment_ids is not None:
                        # packed sequences: the inner attention needs the
                        # whole sequence's ids — a tiny int32 all_gather
                        # (the activations already pay two all_to_alls)
                        segment_ids = lax.all_gather(
                            segment_ids, cfg.seq_axis, axis=1, tiled=True
                        )
                    return ulysses_attention(
                        q, k, v, axis_name=cfg.seq_axis, attn_fn=inner,
                        segment_ids=segment_ids,
                    )

            else:
                attn_fn = functools.partial(
                    causal_attention, window=cfg.attn_window,
                    causal=not cfg.bidirectional, bias=attn_bias,
                )
        return attn_fn(q, k, v, segment_ids=segment_ids)


class MLP(nn.Module):
    """Transformer MLP: column-up / row-down (Megatron pair).

    Activations: gelu (GPT-2 tanh form), gelu_exact (BERT erf form), relu
    (original T5), swiglu (Llama, silu-gated), geglu (T5 v1.1 — gated by
    the TANH-approximate gelu, what HF's "gated-gelu" resolves to).  Gated
    variants use two column projections (gate/up), bias-free (no gated
    checkpoint family carries them).
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        cfg = self.config
        hidden = cfg.mlp_ratio * cfg.d_model
        gated = cfg.mlp in ("swiglu", "geglu")
        if gated:
            gate = TPDense(
                features=hidden, axis_name=cfg.model_axis, style="column",
                dtype=cfg.dtype, use_bias=False, name="gate",
            )(x)
            up = TPDense(
                features=hidden, axis_name=cfg.model_axis, style="column",
                dtype=cfg.dtype, use_bias=False, name="up",
            )(x)
            # geglu's gate is gelu_new (the tanh approximation) — what T5
            # v1.1's "gated-gelu" resolves to in the canonical implementation
            act = (
                nn.silu
                if cfg.mlp == "swiglu"
                else functools.partial(nn.gelu, approximate=True)
            )
            h = act(checkpoint_name(gate, "proj")) * checkpoint_name(up, "proj")
        else:
            h = TPDense(
                features=hidden, axis_name=cfg.model_axis, style="column",
                use_bias=cfg.dense_bias, dtype=cfg.dtype, name="up",
            )(x)
            h = checkpoint_name(h, "proj")
            if cfg.mlp == "relu":
                h = nn.relu(h)
            else:
                h = nn.gelu(h, approximate=cfg.mlp != "gelu_exact")
        y = TPDense(
            features=cfg.d_model, axis_name=cfg.model_axis, style="row",
            dtype=cfg.dtype, use_bias=not gated and cfg.dense_bias, name="down",
        )(h)
        y = checkpoint_name(y, "proj")
        if cfg.dropout_rate > 0.0:
            y = nn.Dropout(rate=cfg.dropout_rate, deterministic=not train)(y)
        return y


class Block(nn.Module):
    """Pre-norm transformer block: x + attn(norm(x)); x + mlp(norm(x))."""

    config: TransformerConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        aux_scale: Optional[jax.Array] = None,
        cache_valid: Optional[jax.Array] = None,
        attn_bias: Optional[jax.Array] = None,
        write_index: Optional[jax.Array] = None,
        block_table: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        if decode and cfg.moe_experts > 0 and cfg.moe_router == "expert_choice":
            # EC routes over the whole token pool; a single-token decode
            # step degenerates to a dense all-expert mixture that resembles
            # nothing the model trained on — refuse loudly
            raise NotImplementedError(
                "incremental decoding with expert-choice routing "
                "(the routing pool collapses to one token per row)"
            )
        attn = Attention(cfg, name="attn")
        mlp_fn = (
            lambda h: MLP(cfg, name="mlp")(h, train=train)
        )
        if cfg.moe_experts > 0:
            from tpu_parallel.models.moe import MoEMLP

            mlp_fn = lambda h: MoEMLP(cfg, name="moe")(
                h, train=train, aux_scale=aux_scale
            )
        attn_kwargs = dict(
            positions=positions,
            segment_ids=segment_ids,
            train=train,
            decode=decode,
            cache_valid=cache_valid,
            attn_bias=attn_bias,
            write_index=write_index,
            block_table=block_table,
        )
        if cfg.prenorm:
            h = make_norm(cfg, "norm_attn")(x).astype(cfg.dtype)
            x = x + attn(h, **attn_kwargs)
            h = make_norm(cfg, "norm_mlp")(x).astype(cfg.dtype)
            x = x + mlp_fn(h)
        else:
            # post-norm (original BERT): normalize the residual SUM
            x = make_norm(cfg, "norm_attn")(x + attn(x, **attn_kwargs)).astype(
                cfg.dtype
            )
            x = make_norm(cfg, "norm_mlp")(x + mlp_fn(x)).astype(cfg.dtype)
        return x


class _ScanBlock(nn.Module):
    """nn.scan target: ``group`` Block(s) per tick, carrying (x, positions,
    segment_ids, aux_scale, cache_valid).  ``block_cls`` lets BlockStack
    substitute the FSDP-wrapped Block (static metadata — both classes produce
    the same variable tree shape, the wrapped one with data-sharded leaves).

    ``group > 1`` (``config.scan_group``) applies that many consecutive
    blocks per scan tick: the carry (the [B, S, d] residual stream) is
    materialized at tick boundaries only, so grouping divides the per-tick
    HBM round-trips by ``group`` while keeping compile size at
    ``n_layers / group`` of the unrolled cost.  Distinct from
    ``scan_unroll`` (which unrolls the LOOP but keeps one block per carry
    round-trip — measured slower, see TransformerConfig.scan_unroll).
    Group 1 keeps the historical single-block param naming ("block")."""

    config: TransformerConfig
    train: bool
    decode: bool = False
    block_cls: Any = Block
    group: int = 1

    @nn.compact
    def __call__(self, carry, _):
        (
            x, positions, segment_ids, aux_scale, cache_valid, attn_bias,
            write_index, block_table,
        ) = carry
        for j in range(self.group):
            name = "block" if self.group == 1 else f"block{j}"
            x = self.block_cls(self.config, name=name)(
                x,
                positions=positions,
                segment_ids=segment_ids,
                train=self.train,
                decode=self.decode,
                aux_scale=aux_scale,
                cache_valid=cache_valid,
                attn_bias=attn_bias,
                write_index=write_index,
                block_table=block_table,
            )
        return (
            (
                x, positions, segment_ids, aux_scale, cache_valid, attn_bias,
                write_index, block_table,
            ),
            None,
        )


def remat_kwargs_for(config: TransformerConfig) -> dict:
    """``nn.remat`` kwargs for a layer stack under ``config.remat_policy``.

    prevent_cse=False is safe (and fastest) under scan for plain remat, but
    with a save-policy XLA can CSE the "recompute" against the forward and
    hoist per-layer score tensors out of the scan — 9G+ of stacked
    [layers, B, H, S, S] buffers.  Keep CSE prevention on when a policy
    narrows the saveable set.
    """
    remat_kwargs = dict(prevent_cse=config.remat_policy != "full")
    if config.remat_policy == "dots":
        remat_kwargs["policy"] = (
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    elif config.remat_policy == "proj":
        remat_kwargs["policy"] = jax.checkpoint_policies.save_only_these_names(
            "proj"
        )
    elif config.remat_policy == "proj_attn":
        remat_kwargs["policy"] = jax.checkpoint_policies.save_only_these_names(
            "proj", "attn"
        )
    return remat_kwargs


class BlockStack(nn.Module):
    """``n_layers`` blocks, optionally remat'd and scanned.

    ``nn.scan`` stacks per-layer params along a leading axis
    (``PARTITION_NAME=None`` keeps flax's Partitioned metadata consistent);
    compile time is then constant in depth.  ``nn.remat`` trades recompute
    for HBM — the standard TPU recipe for 125M+ models.
    """

    config: TransformerConfig
    n_layers: int

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        aux_scale: Optional[jax.Array] = None,
        cache_valid: Optional[jax.Array] = None,
        attn_bias: Optional[jax.Array] = None,
        write_index: Optional[jax.Array] = None,
        block_table: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        remat_kwargs = remat_kwargs_for(cfg)
        # ZeRO-3 over the layers themselves: each tick (scan) or layer
        # (unrolled) gathers ITS params just-in-time and the backward
        # re-gathers under remat, so peak HBM holds one layer's full weights
        # — without this wrap `fsdp=True` sharded only the embeddings/lm_head
        # and the block stack (the bulk of the model) stayed replicated over
        # the data axis.  The wrap sits INSIDE nn.remat: the all_gather is
        # recomputed, never saved.
        base_block: Any = fsdp.maybe_shard(Block, cfg)
        if cfg.scan_layers:
            if seq_parallel_active(cfg):
                # seq-parallel attention output is seq-varying (axis_index /
                # all_to_all inside), so the layer-scan carry must enter
                # seq-varying too — otherwise a size-1 seq axis trips the
                # replication checker (inputs replicated, body output varying)
                from tpu_parallel.core.metrics import pvary_missing, vma_of

                x = pvary_missing(
                    x, vma_of(jax.lax.axis_index(cfg.seq_axis))
                )
            if (
                cfg.moe_experts > 0
                and cfg.moe_dispatch == "alltoall"
                and axis_size_or_none(cfg.model_axis) is not None
            ):
                # same carry-typing rule for the a2a MoE: its closing
                # all_gather leaves the block output model-VARYING (the
                # values are identical across ranks, but the checker can't
                # prove it), so the carry must enter model-varying too
                from tpu_parallel.core.metrics import pvary_missing

                x = pvary_missing(x, (cfg.model_axis,))
            group = max(1, cfg.scan_group)
            if self.n_layers % group != 0:
                raise ValueError(
                    f"scan_group={group} must divide n_layers={self.n_layers}"
                )
            scan_target = _ScanBlock
            if cfg.remat and not decode:
                scan_target = nn.remat(_ScanBlock, **remat_kwargs)
            # no divisibility requirement: lax.scan peels a remainder step
            stacked = nn.scan(
                scan_target,
                variable_axes={"params": 0, "cache": 0, "losses": 0},
                variable_broadcast=False,
                split_rngs={"params": True, "dropout": True},
                length=self.n_layers // group,
                unroll=cfg.scan_unroll,
                _split_transpose=cfg.scan_split_transpose,
                metadata_params={nn.PARTITION_NAME: None},
            )(cfg, train, decode, base_block, group, name="layers")
            (x, _, _, _, _, _, _, _), _ = stacked(
                (
                    x, positions, segment_ids, aux_scale, cache_valid,
                    attn_bias, write_index, block_table,
                ),
                None,
            )
        else:
            # static_argnums: train/decode are Python bools branching the
            # trace (self=0, x=1, positions=2, segment_ids=3, train=4,
            # decode=5) — without it nn.remat traces them as jnp bools and
            # every `if train` raises TracerBoolConversionError
            block_cls = (
                nn.remat(base_block, static_argnums=(4, 5), **remat_kwargs)
                if cfg.remat and not decode
                else base_block
            )
            for i in range(self.n_layers):
                x = block_cls(cfg, name=f"layer_{i}")(
                    x, positions, segment_ids, train, decode, aux_scale,
                    cache_valid, attn_bias, write_index, block_table,
                )
        return x


class Embedding(nn.Module):
    """Token (+ learned positional) embedding, bf16 output."""

    config: TransformerConfig

    @nn.compact
    def __call__(
        self, tokens: jax.Array, positions: Optional[jax.Array] = None
    ) -> jax.Array:
        cfg = self.config
        emb = nn.Embed(
            num_embeddings=cfg.vocab_size,
            features=cfg.d_model,
            dtype=cfg.dtype,
            name="tok",
        )(tokens)
        if cfg.positional == "learned":
            if positions is None:
                local = jnp.arange(tokens.shape[1])
                if seq_parallel_active(cfg):
                    # seq-sharded tokens: offset local positions to global
                    # ones so each shard embeds ITS rows of the table (the
                    # rope analog lives inside Attention)
                    local = local + lax.axis_index(cfg.seq_axis) * tokens.shape[1]
                positions = jnp.broadcast_to(local, tokens.shape)
            pos_emb = nn.Embed(
                num_embeddings=cfg.seq_len,
                features=cfg.d_model,
                dtype=cfg.dtype,
                name="pos",
            )(positions)
            emb = emb + pos_emb
        if cfg.embed_norm:
            # BERT's embeddings.LayerNorm over the summed embedding
            emb = make_norm(cfg, "norm")(emb).astype(cfg.dtype)
        return emb
