"""Weight-only int8 export quantization tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.models import (
    GPTLM,
    QuantizedTensor,
    dequantize_params,
    quantize_params,
    quantized_nbytes,
    tiny_test,
)
from tpu_parallel.models.generate import generate


@pytest.mark.fast
def test_quantize_roundtrip_error_bounded(rng):
    w = jax.random.normal(rng, (64, 128), jnp.float32) * 3.0
    q = quantize_params({"kernel": w}, min_size=1)["kernel"]
    assert isinstance(q, QuantizedTensor) and q.q.dtype == jnp.int8
    back = np.asarray(q.dequantize(jnp.float32))
    # per-channel scale bounds the error at scale/2 = max|w_col| / 254
    col_max = np.abs(np.asarray(w)).max(axis=0)
    assert (np.abs(back - np.asarray(w)) <= col_max / 254 + 1e-6).all()


@pytest.mark.fast
def test_small_and_integer_leaves_pass_through(rng):
    tree = {
        "bias": jnp.ones((8,)),           # too small / 1-D
        "ids": jnp.arange(10_000),        # integer
        "kernel": jax.random.normal(rng, (128, 128)),
    }
    q = quantize_params(tree)
    assert q["bias"] is tree["bias"]
    assert q["ids"] is tree["ids"]
    assert isinstance(q["kernel"], QuantizedTensor)


def test_quantized_model_generates_close(rng):
    """Dequantized int8 weights produce logits close to the originals and
    compress the tree ~4x (fp32 source)."""
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 5), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]
    qparams = quantize_params(params)
    assert quantized_nbytes(qparams) < 0.35 * quantized_nbytes(params)
    restored = dequantize_params(qparams, jnp.float32)
    ref = model.apply({"params": params}, prompt, train=False)
    got = model.apply({"params": restored}, prompt, train=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=0.3, atol=0.3
    )
    # and the generate path accepts the restored tree
    out = generate(model, restored, prompt, max_new_tokens=4, temperature=0.0)
    assert out.shape == (2, 4)


@pytest.mark.fast
def test_int8_npz_roundtrip(rng, tmp_path):
    """save_int8_npz -> load_int8_npz -> dequantize reproduces the dense
    tree within quantization error (the serialized artifact is loadable,
    not write-only)."""
    from tpu_parallel.models.quantize import load_int8_npz, save_int8_npz

    tree = {
        "a": {"kernel": jax.random.normal(rng, (64, 128)), "bias": jnp.ones((8,))},
        "b": {"kernel": jax.random.normal(jax.random.PRNGKey(1), (128, 64))},
    }
    q = quantize_params(tree, min_size=1024)
    path = str(tmp_path / "p.npz")
    save_int8_npz(path, q)
    loaded = load_int8_npz(path)
    back = dequantize_params(loaded, jnp.float32)
    for name in ("a", "b"):
        np.testing.assert_allclose(
            np.asarray(back[name]["kernel"]),
            np.asarray(tree[name]["kernel"]),
            atol=float(np.abs(np.asarray(tree[name]["kernel"])).max()) / 100,
        )
    np.testing.assert_array_equal(np.asarray(back["a"]["bias"]), np.ones(8))
