"""Weight-only int8 quantization for exported (serving) parameters.

Storage/transfer compression for single-device inference params (the output
of :func:`~tpu_parallel.parallel.tp.export_single_device_params`): matrix
kernels become int8 with one fp32 scale per output channel — ~4x smaller
than fp32, ~2x smaller than bf16 on disk and over the wire.
:func:`dequantize_params` restores a tree :func:`generate` accepts.

Scope note: this compresses weights *at rest*.  Runtime HBM during decode
is dominated by the KV cache, which has its own int8 option
(``TransformerConfig.kv_cache_dtype`` — layers.py) read int8-NATIVELY at
attention time (the per-(position, kv-head) scales fold into the score
and value matmuls, so no dequantized cache copy is materialized);
dequantizing the whole weight tree before ``model.apply`` means the live
weights are bf16 as usual.

No reference capability (the reference has no inference path at all).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

Pytree = Any


@struct.dataclass
class QuantizedTensor:
    """int8 payload + fp32 per-output-channel (last dim) scales."""

    q: jax.Array  # int8, original shape
    scale: jax.Array  # fp32, shape (..., 1) broadcast over the last dim

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def absmax_int8(x: jax.Array, axis) -> tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 quantization: ``(int8, fp32 scale)``.

    ``scale = max|x| / 127`` over ``axis`` (kept); all-zero groups produce
    zero payloads with a zero scale.  Shared by the weight-export path here
    and the decode KV cache (models/layers.py) so the numerical recipe
    cannot drift between them.
    """
    a = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(a), axis=axis, keepdims=True) / 127.0
    q = jnp.where(scale > 0, a / jnp.maximum(scale, 1e-30), 0.0)
    return jnp.round(q).astype(jnp.int8), scale


def _quantize_one(w: jax.Array) -> QuantizedTensor:
    # per-output-channel: reduce over every dim except the last (features)
    q, scale = absmax_int8(w, axis=tuple(range(w.ndim - 1)))
    return QuantizedTensor(q=q, scale=scale)


def quantize_params(params: Pytree, min_size: int = 4096) -> Pytree:
    """Quantize every float matrix leaf with >= ``min_size`` elements.

    Biases, norm scales, and other small vectors stay in their original
    dtype (they are tiny and precision-critical); embeddings and all
    projection kernels quantize.  Returns a tree of the same structure with
    :class:`QuantizedTensor` nodes in place of the big matrices.
    """

    def maybe_quantize(x):
        if (
            isinstance(x, jax.Array)
            and jnp.issubdtype(x.dtype, jnp.floating)
            and x.ndim >= 2
            and x.size >= min_size
        ):
            return _quantize_one(x)
        return x

    return jax.tree_util.tree_map(maybe_quantize, params)


def dequantize_params(qparams: Pytree, dtype=jnp.bfloat16) -> Pytree:
    """Restore a :func:`quantize_params` tree to dense ``dtype`` arrays."""

    def maybe_dequantize(x):
        if isinstance(x, QuantizedTensor):
            return x.dequantize(dtype)
        return x

    return jax.tree_util.tree_map(
        maybe_dequantize,
        qparams,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )


def save_int8_npz(path: str, qparams: Pytree) -> None:
    """Serialize a :func:`quantize_params` tree to one ``.npz`` file.

    Quantized leaves store two entries (``<path>::q`` int8,
    ``<path>::scale`` fp32); plain leaves store one.  The inverse is
    :func:`load_int8_npz`.
    """
    import numpy as np

    flat = {}

    def walk(prefix, node):
        if isinstance(node, QuantizedTensor):
            flat[prefix + "::q"] = np.asarray(node.q)
            flat[prefix + "::scale"] = np.asarray(node.scale)
        elif isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node)

    walk("", qparams)
    np.savez(path, **flat)


def load_int8_npz(path: str) -> Pytree:
    """Rebuild the :func:`quantize_params` tree a :func:`save_int8_npz`
    file holds; pass the result to :func:`dequantize_params`."""
    import numpy as np

    data = np.load(path)

    def set_at(tree, keys, value):
        for k in keys[:-1]:
            tree = tree.setdefault(k, {})
        tree[keys[-1]] = value

    tree: dict = {}
    qparts: dict = {}
    for key in data.files:
        if key.endswith(("::q", "::scale")):
            base, part = key.rsplit("::", 1)
            qparts.setdefault(base, {})[part] = data[key]
        else:
            set_at(tree, key.split("/"), jnp.asarray(data[key]))
    for base, parts in qparts.items():
        set_at(
            tree,
            base.split("/"),
            QuantizedTensor(
                q=jnp.asarray(parts["q"]), scale=jnp.asarray(parts["scale"])
            ),
        )
    return tree


def quantized_nbytes(tree: Pytree) -> int:
    """Total serialized bytes of a (possibly quantized) param tree."""
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree)
    )
