"""GPT-2 125M, pure data parallelism (BASELINE config 2: v5e-8)."""

from ml_collections import ConfigDict

from configs.common import model_overrides


def get_config():
    c = ConfigDict()
    c.simulate_cpu_devices = 0
    c.model = "gpt2_125m"
    # round-3 tuned defaults: 0.4344 MFU on v5e-1 (SWEEP_r03.json,
    # docs/05_performance.md) — flash 512x512 tiles, attention residuals
    # saved by the proj_attn remat policy, layers unrolled
    c.model_overrides = model_overrides(
        attn_impl="flash", remat_policy="proj_attn", scan_layers=False
    )
    c.mesh = ConfigDict(dict(data=-1, model=1, pipe=1, seq=1))
    c.global_batch_size = 64
    c.num_minibatches = 1
    c.steps = 100
    c.optimizer = "adamw"  # adamw | lion | sgd
    c.lr_schedule = "cosine"  # cosine | linear | constant
    c.ema_decay = 0.0  # >0 keeps an EMA shadow of params (eval prefers it)
    c.learning_rate = 6e-4
    c.warmup_steps = 20
    c.weight_decay = 0.1
    c.grad_clip = 1.0
    c.seed = 0
    c.log_every = 10
    c.donate = True
    # optional run plumbing (empty = disabled)
    c.checkpoint_dir = ""
    c.checkpoint_every = 100
    c.data_path = ""
    c.data_format = "flat"  # flat | packed (EOS-delimited docs + segment_ids)
    c.eos_id = 50256
    c.eval_steps = 0
    c.eval_every = 0  # >0: periodic eval during fit (uses the held-out split)
    c.keep_best = False  # snapshot lowest-eval-loss state to {checkpoint_dir}/best
    return c