"""Slot-based KV-cache pool for continuous batching.

The pool is ONE cache pytree in the exact per-layer layout the model's
:class:`~tpu_parallel.models.layers.Attention` creates (stacked
``[n_layers, n_slots, seq_len, kv_heads, head_dim]`` payloads under
``nn.scan``, per-slot position tables, int8 scales under
``kv_cache_dtype="int8"``) — the batch axis IS the slot axis.  Requests
own slots for their lifetime: admission prefills the request alone
(batch 1) and row-inserts the fresh cache into the freed slot; retirement
just returns the slot index to the free list (the row is dead weight until
the next insert overwrites all of it, including the position table whose
``-1`` entries keep unwritten slots out of every attention read).

Memory model: pool bytes are fixed at construction —
``n_slots x seq_len`` K/V entries per layer regardless of how many
requests are in flight.  There is no paging/fragmentation (slots are
whole-sequence rows, the simplest correct layout); ``kv_cache_dtype="int8"``
halves the payload exactly as on the static path.

Donation invariant: every WRITE op on the pool (insert / scatter / clear
/ copy_prefix) and every engine decode tick — per-step, verify, and the
fused multi-step tick — DONATES the pool operand, so exactly ONE pool's
worth of device memory is ever live and XLA recycles it in place.  The
flip side is an ownership contract: ``pool.cache`` is the only valid
handle, and a reference to the tree held across any tick or write op
points at deleted buffers (reads raise; pinned in
``tests/test_serving.py::test_fused_tick_donation_invalidates_old_buffers``).
Read-side ops (``extract``, ``stack_prefix``) copy and may be held.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.models.generate import beam_cache_batch_axis


def _leaf_name(path) -> str:
    return path[-1].key if hasattr(path[-1], "key") else str(path[-1])


def insert_rows(pool_cache, fresh_cache, slot):
    """Write a batch-1 prefill cache into row ``slot`` of the pool.

    Pure tree op (traceable; the engine jits it with ``slot`` traced so one
    compile serves every slot).  Batch axes are located by the shared
    name registry (:func:`~tpu_parallel.models.generate.beam_cache_batch_axis`
    — K/V payloads and int8 scales at ndim-4, position tables at ndim-2);
    scalar counters keep the POOL's value: the engine drives decode with
    explicit per-slot positions and ``write_index``, so the shared scalar
    ``cache_index`` is never read on this path.
    """

    def ins(path, pool_leaf, fresh_leaf):
        ax = beam_cache_batch_axis(path, pool_leaf)
        if ax is None:
            return pool_leaf
        return lax.dynamic_update_slice_in_dim(
            pool_leaf, fresh_leaf.astype(pool_leaf.dtype), slot, axis=ax
        )

    return jax.tree_util.tree_map_with_path(ins, pool_cache, fresh_cache)


def scatter_rows(pool_cache, fresh_cache, slots):
    """Write the rows of a batch-N prefill cache into pool rows ``slots``
    [N] — the batched-prefill generalization of :func:`insert_rows` (one
    scatter per leaf instead of N dynamic-slice programs).

    Traceable with ``slots`` traced.  Rows whose slot is OUT OF RANGE
    (the engine passes ``n_slots`` for a padded prefill batch's dummy
    rows) are DROPPED by JAX's default scatter semantics — the pool leaf
    keeps its value, which is exactly the discard the padding wants.
    """

    def ins(path, pool_leaf, fresh_leaf):
        ax = beam_cache_batch_axis(path, pool_leaf)
        if ax is None:
            return pool_leaf
        idx = (slice(None),) * ax + (slots,)
        return pool_leaf.at[idx].set(fresh_leaf.astype(pool_leaf.dtype))

    return jax.tree_util.tree_map_with_path(ins, pool_cache, fresh_cache)


def extract_rows(pool_cache, slot, n: int = 1):
    """Slice ``n`` consecutive rows starting at ``slot`` out of the pool —
    a batch-``n`` cache tree in the model's own layout (scalar counters
    pass through unchanged; the engine never reads them).  The chunked
    prefill's read side: extract the slot's row, extend it one chunk
    (:func:`~tpu_parallel.models.generate.prefill_extend_step`), scatter
    it back."""

    def ext(path, leaf):
        ax = beam_cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        return lax.dynamic_slice_in_dim(leaf, slot, n, axis=ax)

    return jax.tree_util.tree_map_with_path(ext, pool_cache)


def clear_rows(pool_cache, slot):
    """Invalidate pool row ``slot``: every position-table entry to -1, so
    no query ever attends the row's (stale) K/V again.  The K/V payloads
    are left untouched — dead bytes until overwritten.  Used before a
    chunked prefill starts writing a freed slot incrementally (a whole-row
    insert is not available until the LAST chunk; the stale occupant must
    not leak into the chunks' attention reads meanwhile)."""

    def clr(path, leaf):
        if not _leaf_name(path).startswith(("cached_pos", "cross_mask")):
            return leaf
        ax = beam_cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        row_shape = leaf.shape[:ax] + (1,) + leaf.shape[ax + 1:]
        return lax.dynamic_update_slice_in_dim(
            leaf, jnp.full(row_shape, -1, leaf.dtype), slot, axis=ax
        )

    return jax.tree_util.tree_map_with_path(clr, pool_cache)


def copy_prefix_rows(pool_cache, prefix_cache, slot, length):
    """Copy a stored prefix row into pool row ``slot``, trimming validity
    to the first ``length`` positions: K/V payloads copy whole (slots
    beyond ``length`` are dead bytes), the position table copies masked to
    -1 beyond ``length`` so ONLY the prefix is attendable.  The whole-row
    copy doubles as the slot's invalidation of its previous occupant.

    Exactness: cached K/V is a pure function of (token, position, params)
    — including the int8 path's per-(position, kv-head) quantization — so
    a copied prefix row is bit-identical to recomputing the prefill.
    """

    def ins(path, pool_leaf, fresh_leaf):
        ax = beam_cache_batch_axis(path, pool_leaf)
        if ax is None:
            return pool_leaf
        fresh_leaf = fresh_leaf.astype(pool_leaf.dtype)
        if _leaf_name(path).startswith(("cached_pos", "cross_mask")):
            valid = jnp.arange(fresh_leaf.shape[-1]) < length
            fresh_leaf = jnp.where(valid, fresh_leaf, -1)
        return lax.dynamic_update_slice_in_dim(
            pool_leaf, fresh_leaf, slot, axis=ax
        )

    return jax.tree_util.tree_map_with_path(ins, pool_cache, prefix_cache)


def _pool_cache_shapes(model, params, n_slots: int):
    """abstract shapes of the model's decode cache at batch ``n_slots``,
    via ``jax.eval_shape`` — no forward pass runs.  The ONE shape probe
    behind both :func:`empty_pool` and :func:`cache_partition_specs`, so
    the allocated pool tree and its partition specs cannot drift."""

    def probe():
        tok = jnp.zeros((n_slots, 1), jnp.int32)
        pos = jnp.zeros((n_slots, 1), jnp.int32)
        _, variables = model.apply(
            {"params": params},
            tok,
            positions=pos,
            train=False,
            decode=True,
            hidden_only=True,
            mutable=["cache"],
        )
        return variables["cache"]

    return jax.eval_shape(probe)


def empty_pool(model, params, n_slots: int, shardings=None):
    """Allocate the pool cache: the model's own decode-cache structure at
    batch ``n_slots``, zero-filled, with every position-table entry at -1
    (no slot attends until a request's prefill row is inserted).

    Only the cache STRUCTURE comes from the model, so any config (GQA
    widths, int8 scales, unrolled vs scanned stacks) produces its
    matching pool.  ``shardings`` (a matching tree of ``jax.sharding``
    objects) places each leaf sharded at BIRTH — allocating host-side and
    ``device_put``-ing per leaf, so a TP-sharded pool never transits one
    device whole (a pool sized to the per-device share would otherwise
    OOM device 0 at construction).
    """
    import numpy as np

    shapes = _pool_cache_shapes(model, params, n_slots)
    if shardings is None:
        def alloc(path, leaf):
            if _leaf_name(path).startswith("cached_pos"):
                return jnp.full(leaf.shape, -1, leaf.dtype)
            return jnp.zeros(leaf.shape, leaf.dtype)

        return jax.tree_util.tree_map_with_path(alloc, shapes)

    def alloc_sharded(path, leaf, sharding):
        fill = -1 if _leaf_name(path).startswith("cached_pos") else 0
        host = np.full(leaf.shape, fill, leaf.dtype)
        return jax.device_put(host, sharding)

    return jax.tree_util.tree_map_with_path(alloc_sharded, shapes, shardings)


def cache_partition_specs(model, params, n_slots: int, mesh):
    """PartitionSpecs for every pool-cache leaf under ``mesh`` — the
    out/in specs the sharded engine threads through
    :func:`~tpu_parallel.models.generate.build_sharded_serving`.

    K/V payloads and their int8 scales shard over the model (TP) axis at
    the kv-head dim (ndim-2) exactly as activations do; position tables and
    scalar counters are replicated.  Slots are NOT sharded over the data
    axis — admission is a per-slot host decision, so every data rank holds
    every slot (documented engine caveat: data ranks duplicate decode
    work).  When the mesh has no model axis the payloads are replicated
    too.
    """
    from jax.sharding import PartitionSpec as P

    model_axis = model.config.model_axis
    if model_axis not in mesh.axis_names:
        model_axis = None
    shapes = _pool_cache_shapes(model, params, n_slots)

    def spec(path, leaf):
        name = _leaf_name(path)
        if model_axis is not None and name.startswith(
            ("cached_key", "cached_value", "cross_key", "cross_value")
        ):
            parts = [None] * leaf.ndim
            parts[leaf.ndim - 2] = model_axis  # the kv-head dim
            return P(*parts)
        return P()

    return jax.tree_util.tree_map_with_path(spec, shapes)


def stack_prefix_rows(rows, length):
    """Stack batch-1 prefix rows into one batch-N cache tree, position
    tables trimmed to the first ``length`` entries (-1 beyond) — the
    BATCHED prefix-hit landing: N same-length hits extend as one padded
    model call instead of N single-row round-trips.

    ``rows`` is a tuple of stored prefix rows (NOT donated — they stay
    live in the prefix cache; the concatenate copies).  Scalar leaves take
    the first row's value (unread).
    """

    def stk(path, *leaves):
        ax = beam_cache_batch_axis(path, leaves[0])
        if ax is None:
            return leaves[0]
        out = jnp.concatenate(leaves, axis=ax)
        if _leaf_name(path).startswith(("cached_pos", "cross_mask")):
            out = jnp.where(jnp.arange(out.shape[-1]) < length, out, -1)
        return out

    return jax.tree_util.tree_map_with_path(stk, *rows)


class CachePool:
    """Host-side slot bookkeeping + the device cache pytree.

    ``acquire()``/``release()`` manage the free list; ``insert()`` commits
    a prefilled request into its slot.  The device tree lives at
    ``self.cache`` and is REPLACED (functionally) by every insert and by
    every engine decode tick.
    """

    def __init__(self, model, params, n_slots: int, insert_fn=None,
                 shardings=None, row_fns=None):
        if n_slots < 1:
            raise ValueError(f"n_slots={n_slots} < 1")
        self.n_slots = n_slots
        self.cache = empty_pool(model, params, n_slots, shardings=shardings)
        self._free: List[int] = list(range(n_slots))
        # donate the pool operand: the old tree is dead after every insert,
        # and without donation XLA keeps a full second pool copy alive
        self._insert = (
            insert_fn
            if insert_fn is not None
            else jax.jit(insert_rows, donate_argnums=0)
        )
        # row-level fast-path ops (scatter/extract/clear/copy_prefix),
        # injectable so the engine's lru-cached jits are shared per model
        if row_fns is None:
            row_fns = default_row_fns()
        (self._scatter, self._extract, self._clear,
         self._copy_prefix, self.stack_prefix) = row_fns

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self._free) / self.n_slots

    def acquire(self) -> Optional[int]:
        """Claim a free slot index (lowest-first, deterministic), or None."""
        if not self._free:
            return None
        return self._free.pop(0)

    def release(self, slot: int) -> None:
        if slot in self._free or not (0 <= slot < self.n_slots):
            raise ValueError(f"bad release of slot {slot}")
        self._free.append(slot)
        self._free.sort()

    def insert(self, fresh_cache, slot: int) -> None:
        """Row-insert a batch-1 prefill cache into ``slot``."""
        self.cache = self._insert(self.cache, fresh_cache, jnp.int32(slot))

    def scatter(self, fresh_cache, slots) -> None:
        """Scatter a batch-N prefill cache's rows into ``slots`` [N]; pass
        ``n_slots`` for dummy rows (dropped — see :func:`scatter_rows`)."""
        self.cache = self._scatter(
            self.cache, fresh_cache, jnp.asarray(slots, jnp.int32)
        )

    def extract(self, slot: int):
        """Pull one slot's row out as a batch-1 cache tree (chunked-prefill
        read side; also the prefix cache's capture path)."""
        return self._extract(self.cache, jnp.int32(slot))

    def clear(self, slot: int) -> None:
        """Invalidate a slot's position table before incremental writes."""
        self.cache = self._clear(self.cache, jnp.int32(slot))

    def copy_prefix(self, prefix_cache, slot: int, length: int) -> None:
        """Land a stored prefix row (first ``length`` positions valid)
        into ``slot`` — the prefix-reuse admission skips recomputing those
        tokens entirely."""
        self.cache = self._copy_prefix(
            self.cache, prefix_cache, jnp.int32(slot), jnp.int32(length)
        )

    def assert_slot_aligned(self, slot: int) -> None:
        """Assert the ALIGNED-layout invariant speculative decoding's
        no-rollback story rests on: every valid entry of ``slot``'s
        position table stores exactly its own column index
        (``pos[col] in {-1, col}``).

        Why this is THE invariant: the engine always writes position p at
        column p (prefill from 0, decode/verify at ``write_index == pos``),
        so a REJECTED draft's stale K/V at column c holds position c — and
        c necessarily exceeds the slot's accepted frontier.  Any later
        forward writes its tokens (columns L..L+T-1) before its attention
        read, so surviving stale columns satisfy c >= L+T > every query
        position and the ``kp <= qp`` mask keeps them invisible; -1
        entries (pads, cleared rows) never attend at all.  If alignment
        ever broke — a stale column holding a SMALLER position — stale
        K/V could silently enter attention, which is why this is an
        assert, not a repair.  Debug/test aid (one small device->host
        fetch per call): the engine runs it per verify tick under
        ``spec_check_invariants=True``.
        """
        import numpy as np

        def check(path, leaf):
            if not _leaf_name(path).startswith("cached_pos"):
                return leaf
            ax = beam_cache_batch_axis(path, leaf)
            row = np.asarray(
                lax.dynamic_slice_in_dim(leaf, slot, 1, axis=ax)
            ).reshape(-1, leaf.shape[-1])
            cols = np.arange(leaf.shape[-1])[None, :]
            bad = (row != -1) & (row != cols)
            assert not bad.any(), (
                f"slot {slot} position table misaligned at "
                f"(layer, col) {np.argwhere(bad)[:4].tolist()}: stale "
                f"columns would enter attention (pos != col)"
            )
            return leaf

        jax.tree_util.tree_map_with_path(check, self.cache)


def default_row_fns():
    """Jitted (scatter, extract, clear, copy_prefix, stack_prefix) with
    the pool operand donated on every WRITE op (the old pool tree is dead
    the moment the call returns; extract reads only, and stack_prefix's
    inputs stay live in the prefix cache — neither donates)."""
    return (
        jax.jit(scatter_rows, donate_argnums=0),
        jax.jit(extract_rows, static_argnums=2),
        jax.jit(clear_rows, donate_argnums=0),
        jax.jit(copy_prefix_rows, donate_argnums=0),
        jax.jit(stack_prefix_rows),
    )
