"""Checkpoint / resume on top of orbax — the TPU-native answer.

The reference has no persistence at all (SURVEY.md §5: "no orbax/flax
serialization anywhere"; its ``TrainState`` is checkpointable-by-construction
but nothing saves it).  This module supplies the capability: sharded
``TrainState`` pytrees (including ``nn.Partitioned``-boxed leaves) saved with
orbax and restored *onto the same mesh layout* via an abstract target derived
from the trainer's init function — every leaf comes back with its
NamedSharding, so restore never materializes a full replica on one host.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import jax
import orbax.checkpoint as ocp

Pytree = Any


class Checkpointer:
    """Thin orbax wrapper bound to one run directory.

    ``abstract_state``: pytree of ShapeDtypeStruct (with shardings) matching
    the live state — build it with :func:`abstract_state_of`.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Pytree, *, wait: bool = False) -> None:
        self.manager.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()

    def restore(self, abstract_state: Pytree, step: Optional[int] = None) -> Pytree:
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {self.directory}")
        return self.manager.restore(
            step, args=ocp.args.StandardRestore(abstract_state)
        )

    @property
    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()


def abstract_state_of(init_fn: Callable, *example_args) -> Pytree:
    """Abstract (shape/dtype/sharding) twin of ``init_fn(*example_args)``.

    ``init_fn`` should be the jitted sharded init from
    ``build_train_functions`` — its output shardings become the restore
    layout.
    """
    return jax.eval_shape(init_fn, *example_args)
