"""Daemon crash/drain soak: kill -9 the serving process mid-traffic,
restart it, and PROVE the journal-replay contract.

Three entry modes:

- (default) ``--soak``: the acceptance gate.  For each seeded trial:
  start the daemon as a real subprocess, feed it a seeded request
  schedule over HTTP (every request carries a client dedupe token),
  SIGKILL the process at a seeded point mid-traffic, restart it on the
  SAME journal, retry every submission idempotently (real clients retry
  on connection loss), run the remainder out, and assert:

  1. **zero lost accepted requests** — every journaled submit reaches
     exactly one ``finished`` terminal across the two process lives;
  2. **zero duplicate completions** — each dedupe token maps to exactly
     one journal submit and one terminal (retries after the crash
     dedupe instead of re-admitting);
  3. **bitwise token parity** — every completed stream equals the
     static greedy reference, so the crash+replay (journal prefix +
     forced-prefix recompute) changed NOTHING about the output;
  4. **zero leaked KV reservations** — ``/statez`` shows
     ``inflight_tokens == 0`` and every replica's slots/queues empty
     after quiesce;
  5. **graceful exit** — SIGTERM drains and exits 0 inside the grace
     window, with a clean shutdown record as the journal's last word.

  ``--record DAEMON_r01.json`` writes the per-trial evidence.

- ``--smoke``: the fast CI gate (wired into ``scripts/check_all.py``
  and tier-1 via ``tests/test_daemon.py``): one subprocess — start,
  healthz, submit over HTTP, stream to completion, SIGTERM, assert a
  clean drained exit 0 and a clean journal.  No kill -9 (that is the
  soak's job); one model build is the whole cost.

- ``--serve``: INTERNAL child mode — build the tiny-model fleet, wrap
  it in :class:`~tpu_parallel.daemon.ServingDaemon` + HTTP server,
  write the ready file, install signals, pump until shut down, exit
  with ``daemon.run()``'s code.  The parent modes spawn this.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

DEFAULT_NEW_TOKENS = 8
SOAK_NEW_TOKENS = 20  # long enough that a seeded kill lands mid-stream
READY_TIMEOUT = 300.0  # cold jax import + compile on a 1-core box


# -- HTTP client helpers -----------------------------------------------------


def http_json(method, url, body=None, timeout=120.0):
    """One JSON request; returns (status_code, payload) and never
    raises on HTTP error codes (connection errors DO raise)."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


def wait_ready(ready_file, proc, timeout=READY_TIMEOUT):
    """Poll for the child's ready file; returns its payload dict."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon child exited rc={proc.returncode} before ready"
            )
        if os.path.exists(ready_file):
            try:
                with open(ready_file) as fh:
                    info = json.load(fh)
                if "port" in info:
                    return info
            except (ValueError, OSError):
                pass  # mid-write
        time.sleep(0.05)
    raise RuntimeError(f"daemon child not ready within {timeout}s")


def spawn_daemon(args, journal, ready_file, extra=()):
    """Start the --serve child with this script's interpreter/env."""
    if os.path.exists(ready_file):
        os.remove(ready_file)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--serve",
        "--journal", journal, "--ready-file", ready_file,
        "--replicas", str(args.replicas), "--slots", str(args.slots),
        "--grace", str(args.grace), "--fsync-batch", str(args.fsync_batch),
        *extra,
    ]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(cmd, env=env)


# -- schedule + references ---------------------------------------------------


def make_schedule(seed, n_requests, new_tokens):
    """Seeded prompts + dedupe tokens (pure function of seed)."""
    rnd = random.Random(seed)
    schedule = []
    for i in range(n_requests):
        length = rnd.randrange(3, 12)
        prompt = [rnd.randrange(1, 250) for _ in range(length)]
        schedule.append({
            "dedupe_token": f"soak-{seed}-{i}",
            "prompt": prompt,
            "max_new_tokens": new_tokens,
        })
    return schedule


def greedy_references(schedule):
    """Static-generate greedy continuation for every prompt — the
    parity oracle the daemon's crash+replay output must match."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_parallel.models import GPTLM, tiny_test
    from tpu_parallel.models.generate import generate

    cfg = tiny_test(remat=False)
    model = GPTLM(cfg)
    probe = jnp.zeros((1, 16), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    refs = {}
    for entry in schedule:
        prompt = entry["prompt"]
        # generate() returns [batch, max_new_tokens] — continuation only
        cont = np.asarray(generate(
            model, params, jnp.asarray(prompt, jnp.int32)[None, :],
            max_new_tokens=entry["max_new_tokens"],
        ))[0]
        refs[entry["dedupe_token"]] = [int(t) for t in cont]
    return refs


# -- the serve child ---------------------------------------------------------


def serve(args):
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(REPO_ROOT, ".pytest_xla_cache"),
    )
    from tpu_parallel.cluster import Frontend, FrontendConfig
    from tpu_parallel.daemon import (
        DaemonConfig,
        DaemonHTTPServer,
        ServingDaemon,
    )
    from tpu_parallel.models import GPTLM, tiny_test
    from tpu_parallel.obs.registry import MetricRegistry
    from tpu_parallel.serving import SchedulerConfig, ServingEngine

    cfg = tiny_test(remat=False)
    model = GPTLM(cfg)
    probe = jax.numpy.zeros((1, 16), jax.numpy.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]

    def frontend_factory(clock):
        engines = [
            ServingEngine(
                model, params, n_slots=args.slots,
                scheduler=SchedulerConfig(max_prefills_per_tick=2),
            )
            for _ in range(args.replicas)
        ]
        return Frontend(
            engines, router="least",
            config=FrontendConfig(restart=None),
            clock=clock, registry=MetricRegistry(),
        )

    daemon = ServingDaemon(
        frontend_factory, args.journal,
        config=DaemonConfig(
            grace_seconds=args.grace, fsync_batch=args.fsync_batch,
        ),
    )
    server = DaemonHTTPServer(daemon, port=args.port).start()
    daemon.install_signals()
    with open(args.ready_file + ".tmp", "w") as fh:
        json.dump({"port": server.port, "pid": os.getpid()}, fh)
    os.replace(args.ready_file + ".tmp", args.ready_file)
    rc = daemon.run()
    server.stop()
    return rc


# -- invariants --------------------------------------------------------------


def journal_invariants(journal_path, problems):
    """Scan the journal the way recovery does and check the no-loss /
    no-duplicate bookkeeping.  Returns the folded state."""
    from tpu_parallel.daemon import load_state

    state = load_state(journal_path)
    by_token = {}
    for rid in state.order:
        entry = state.entries[rid]
        tok = entry.dedupe_token
        if tok is not None:
            by_token.setdefault(tok, []).append(rid)
    for tok, rids in by_token.items():
        if len(rids) != 1:
            problems.append(
                f"dedupe token {tok} journaled {len(rids)} submits "
                f"({rids}) — duplicate admission"
            )
    for entry in state.unfinished:
        problems.append(
            f"request {entry.request_id} journaled accepted but never "
            "reached a terminal — lost accepted work"
        )
    return state


def state_leak_check(port, problems, label):
    code, payload = http_json(
        "GET", f"http://127.0.0.1:{port}/statez"
    )
    if code != 200:
        problems.append(f"{label}: /statez returned {code}")
        return
    cluster = payload["cluster"]
    if cluster["inflight_tokens"] != 0:
        problems.append(
            f"{label}: leaked token reservations: "
            f"{cluster['inflight_tokens']}"
        )
    for rep in cluster["replicas"]:
        if rep["active_slots"] or rep["queue_depth"]:
            problems.append(
                f"{label}: replica {rep['replica']} not quiesced: "
                f"slots={rep['active_slots']} queue={rep['queue_depth']}"
            )


def stop_gracefully(proc, grace, problems, label):
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=grace + 60)
    except subprocess.TimeoutExpired:
        proc.kill()
        problems.append(f"{label}: SIGTERM did not exit within grace")
        return
    if rc != 0:
        problems.append(f"{label}: drain exit code {rc} != 0")


# -- modes -------------------------------------------------------------------


def run_smoke(tmpdir=None, keep=False):
    """start -> submit -> stream -> SIGTERM drain -> clean exit.  The
    fast gate check_all and tier-1 run.  Returns a problem list."""
    import tempfile

    from tpu_parallel.daemon import REC_SHUTDOWN, read_journal

    problems = []
    tmpdir = tmpdir or tempfile.mkdtemp(prefix="daemon_smoke_")
    journal = os.path.join(tmpdir, "journal.jsonl")
    ready = os.path.join(tmpdir, "ready.json")
    args = argparse.Namespace(
        replicas=1, slots=2, grace=60.0, fsync_batch=8,
    )
    proc = spawn_daemon(args, journal, ready)
    try:
        info = wait_ready(ready, proc)
        port = info["port"]
        code, payload = http_json(
            "GET", f"http://127.0.0.1:{port}/healthz"
        )
        if code != 200 or not payload.get("ok"):
            problems.append(f"healthz {code}: {payload}")
        schedule = make_schedule(seed=7, n_requests=2,
                                 new_tokens=DEFAULT_NEW_TOKENS)
        rids = []
        for entry in schedule:
            code, rec = http_json(
                "POST", f"http://127.0.0.1:{port}/v1/submit", entry
            )
            if code != 200:
                problems.append(f"submit {code}: {rec}")
                continue
            rids.append(rec["request_id"])
        # idempotence: resubmitting the first token dedupes
        code, rec = http_json(
            "POST", f"http://127.0.0.1:{port}/v1/submit", schedule[0]
        )
        if code != 200 or rec["request_id"] != rids[0]:
            problems.append(f"dedupe resubmit mismatched: {code} {rec}")
        deadline = time.monotonic() + 120
        for rid in rids:
            while time.monotonic() < deadline:
                code, rec = http_json(
                    "GET", f"http://127.0.0.1:{port}/v1/result/{rid}"
                )
                if code == 200 and rec["status"] == "finished":
                    if len(rec["tokens"]) != DEFAULT_NEW_TOKENS:
                        problems.append(
                            f"{rid}: {len(rec['tokens'])} tokens != "
                            f"{DEFAULT_NEW_TOKENS}"
                        )
                    break
                time.sleep(0.05)
            else:
                problems.append(f"{rid}: never finished")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metricsz", timeout=30
        ) as resp:
            metrics_text = resp.read().decode()
        if "daemon_journal_records_total" not in metrics_text:
            problems.append("metricsz missing daemon_* series")
        if rids:
            # SSE replay of a finished stream: N token events + a
            # finished event with the typed reason
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/stream/{rids[0]}"
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                events = [
                    json.loads(line[len(b"data: "):])
                    for line in resp.read().split(b"\n")
                    if line.startswith(b"data: ")
                ]
            toks = [e["token"] for e in events if "token" in e]
            if len(toks) != DEFAULT_NEW_TOKENS or not events[-1].get(
                "finished"
            ):
                problems.append(
                    f"stream replay malformed: {len(toks)} tokens, "
                    f"tail {events[-1] if events else None}"
                )
        state_leak_check(port, problems, "smoke")
        stop_gracefully(proc, args.grace, problems, "smoke")
        records, torn = read_journal(journal)
        if torn:
            problems.append(f"{torn} torn record(s) after a clean exit")
        last = records[-1] if records else {}
        if last.get("record") != REC_SHUTDOWN or not last.get("clean"):
            problems.append(
                f"journal's last word is {last} — expected a clean "
                "shutdown record"
            )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if not keep and not problems:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    return problems


def run_soak(args):
    """The seeded kill-9 / restart / drain acceptance soak."""
    from tpu_parallel.daemon import load_state

    record = {"bench": "daemon_soak", "trials": []}
    problems = []
    refs_cache = {}
    for trial in range(args.trials):
        seed = args.seed + trial
        rnd = random.Random(seed ^ 0xD43)
        tmpdir = os.path.join(
            args.workdir or "/tmp", f"daemon_soak_{os.getpid()}_{seed}"
        )
        os.makedirs(tmpdir, exist_ok=True)
        journal = os.path.join(tmpdir, "journal.jsonl")
        ready = os.path.join(tmpdir, "ready.json")
        if os.path.exists(journal):
            os.remove(journal)
        schedule = make_schedule(seed, args.requests, args.new)
        if seed not in refs_cache:
            refs_cache[seed] = greedy_references(schedule)
        refs = refs_cache[seed]
        trial_problems = []

        # ---- life 1: accept traffic, SIGKILL at a seeded point
        proc = spawn_daemon(args, journal, ready)
        info = wait_ready(ready, proc)
        port = info["port"]
        kill_after = rnd.randrange(2, max(3, args.requests - 2))
        accepted = {}
        killed = False
        for i, entry in enumerate(schedule):
            try:
                code, rec = http_json(
                    "POST", f"http://127.0.0.1:{port}/v1/submit", entry
                )
            except (urllib.error.URLError, OSError):
                break  # the daemon is gone (we killed it)
            if code == 200:
                accepted[entry["dedupe_token"]] = rec["request_id"]
            else:
                trial_problems.append(
                    f"life1 submit {i} rejected {code}: {rec}"
                )
            if i + 1 == kill_after:
                # let some tokens stream so the kill lands mid-request
                time.sleep(rnd.uniform(0.2, 0.6))
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)
                killed = True
                break
        if not killed:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        durable = load_state(journal)
        life1 = {
            "accepted": len(accepted),
            "kill_after": kill_after,
            "durable_submits": len(durable.order),
            "durable_unfinished": len(durable.unfinished),
            "torn_records": durable.torn_records,
        }
        if len(durable.order) < len(accepted):
            trial_problems.append(
                f"life1: {len(accepted)} accepts acknowledged but only "
                f"{len(durable.order)} journaled — the WAL lied"
            )

        # ---- life 2: restart on the same journal, idempotent retries
        proc = spawn_daemon(args, journal, ready)
        info = wait_ready(ready, proc)
        port = info["port"]
        dedupe_hits = 0
        all_rids = {}
        for entry in schedule:
            code, rec = http_json(
                "POST", f"http://127.0.0.1:{port}/v1/submit", entry
            )
            if code != 200:
                trial_problems.append(
                    f"life2 submit rejected {code}: {rec}"
                )
                continue
            tok = entry["dedupe_token"]
            all_rids[tok] = rec["request_id"]
            if tok in accepted:
                if rec["request_id"] != accepted[tok]:
                    trial_problems.append(
                        f"life2: dedupe {tok} re-admitted as "
                        f"{rec['request_id']} != {accepted[tok]}"
                    )
                else:
                    dedupe_hits += 1
        deadline = time.monotonic() + 240
        finished = {}
        pending = dict(all_rids)
        while pending and time.monotonic() < deadline:
            for tok, rid in list(pending.items()):
                code, rec = http_json(
                    "GET", f"http://127.0.0.1:{port}/v1/result/{rid}"
                )
                if code == 200 and rec["status"] in (
                    "finished", "failed", "cancelled", "rejected",
                    "expired",
                ):
                    finished[tok] = rec
                    del pending[tok]
            time.sleep(0.05)
        for tok, rid in pending.items():
            trial_problems.append(f"{tok} ({rid}): never terminal")

        # ---- invariants
        for tok, rec in finished.items():
            if rec["status"] != "finished":
                trial_problems.append(
                    f"{tok}: status {rec['status']} "
                    f"({rec['finish_reason']}) — lost accepted work"
                )
                continue
            if rec["tokens"] != refs[tok]:
                trial_problems.append(
                    f"{tok}: tokens diverge from the greedy reference "
                    "through crash+replay"
                )
        state_leak_check(port, trial_problems, f"trial{trial}")
        stop_gracefully(
            proc, args.grace, trial_problems, f"trial{trial}"
        )
        state = journal_invariants(journal, trial_problems)
        trial_rec = {
            "seed": seed,
            "life1": life1,
            "dedupe_hits_on_retry": dedupe_hits,
            "recoveries": state.recoveries,
            "journal_records": state.next_seq,
            "finished": sum(
                1 for r in finished.values()
                if r["status"] == "finished"
            ),
            "requests": args.requests,
            "problems": list(trial_problems),
        }
        record["trials"].append(trial_rec)
        problems.extend(trial_problems)
        if not trial_problems:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
        print(
            f"trial {trial} (seed {seed}): accepted={len(accepted)} "
            f"kill_after={kill_after} dedupe_hits={dedupe_hits} "
            f"finished={trial_rec['finished']}/{args.requests} "
            f"problems={len(trial_problems)}"
        )
    caught = sum(
        t["life1"]["durable_unfinished"] for t in record["trials"]
    )
    if caught == 0:
        problems.append(
            "no trial caught accepted-but-unfinished work at the kill "
            "point — the soak proved nothing about recovery; lengthen "
            "--new or add trials"
        )
    record["unfinished_at_kill_total"] = caught
    record["ok"] = not problems
    if args.record:
        with open(args.record, "w") as fh:
            json.dump(record, fh, indent=2)
            fh.write("\n")
        print(f"record: {args.record}")
    return problems


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true",
                    help="INTERNAL: run the daemon child process")
    ap.add_argument("--smoke", action="store_true",
                    help="fast gate: start, submit, SIGTERM drain, "
                         "assert clean exit (no kill -9)")
    ap.add_argument("--soak", action="store_true",
                    help="seeded kill-9/restart soak (the default)")
    ap.add_argument("--journal", type=str, default="")
    ap.add_argument("--ready-file", type=str, default="")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--grace", type=float, default=60.0)
    ap.add_argument("--fsync-batch", type=int, default=8)
    ap.add_argument("--requests", type=int, default=10)
    ap.add_argument("--new", type=int, default=SOAK_NEW_TOKENS)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workdir", type=str, default="")
    ap.add_argument("--record", type=str, default="")
    args = ap.parse_args()

    if args.serve:
        if not args.journal or not args.ready_file:
            ap.error("--serve needs --journal and --ready-file")
        sys.exit(serve(args))
    if args.smoke:
        problems = run_smoke()
    else:
        problems = run_soak(args)
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(
            f"daemon_bench: {len(problems)} INVARIANT VIOLATION(S)",
            file=sys.stderr,
        )
        sys.exit(1)
    print("daemon_bench: OK")


if __name__ == "__main__":
    main()
