"""PRNG discipline across mesh axes.

Capability parity: ``fold_rng_over_axis`` (reference ``data_paral.py:28-34``),
generalized to any number of mesh axes so DP x TP x PP composition gets a
well-defined key on every device.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
from jax import lax


def fold_rng_over_axis(rng: jax.Array, axis_names: Union[str, Sequence[str]]) -> jax.Array:
    """Derive a device-unique key by folding the mesh position into ``rng``.

    Use for anything that must differ per device (dropout on different data
    shards, per-stage init).  Leave the key unfolded for anything that must be
    identical across an axis (replicated init).

    Unbound axes are skipped — the same degrade-gracefully contract as the
    structural-TP layers: a loss/model built for a mesh runs under plain
    ``jit`` (single device, no shard_map) with every fold a no-op, instead
    of dying in ``axis_index``.  The skip is deliberately permissive (ANY
    unbound name, so renamed config axes keep working mesh-free); typo'd
    axis names are caught where config meets mesh instead — the Trainer
    validates every config axis against the mesh's axis names at init.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    for name in axis_names:
        try:
            idx = lax.axis_index(name)
        except NameError:
            continue
        rng = jax.random.fold_in(rng, idx)
    return rng


def split_rng_like(rng: jax.Array, tree) -> "jax.Array":
    """Split ``rng`` into a pytree of keys matching ``tree``'s structure."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    return jax.tree_util.tree_unflatten(treedef, list(keys))
