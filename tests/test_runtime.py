"""Tests for runtime bootstrap and mesh construction."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.runtime import (
    AXIS_ORDER,
    MeshConfig,
    factor_mesh,
    make_mesh,
    process_info,
)


def test_simulated_devices(devices):
    assert len(devices) == 8
    assert all(d.platform == "cpu" for d in devices)


def test_process_info(devices):
    info = process_info()
    assert info["process_count"] == 1
    assert info["global_device_count"] == 8


def test_mesh_shapes(devices):
    mesh = make_mesh(MeshConfig(data=8))
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1
    mesh3 = make_mesh(MeshConfig(data=2, model=2, pipe=2))
    assert mesh3.shape == dict(pipe=2, data=2, seq=1, model=2)
    assert mesh3.axis_names == AXIS_ORDER


def test_mesh_resolves_remaining(devices):
    cfg = MeshConfig(data=-1, model=2).resolved(8)
    assert cfg.data == 4


def test_mesh_rejects_bad_shape(devices):
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(data=3, model=2))
    with pytest.raises(ValueError):
        MeshConfig(data=-1, model=3).resolved(8)


def test_factor_mesh():
    cfg = factor_mesh(8, want_model=2, want_pipe=2)
    assert (cfg.pipe, cfg.data, cfg.model) == (2, 2, 2)
    cfg = factor_mesh(6, want_model=4, want_pipe=4)
    assert cfg.model * cfg.pipe * cfg.data == 6
    cfg = factor_mesh(1, want_model=8, want_pipe=8)
    assert (cfg.pipe, cfg.data, cfg.model) == (1, 1, 1)


def test_collective_on_mesh(mesh_data8):
    f = jax.jit(
        jax.shard_map(
            lambda x: jax.lax.psum(x, "data"),
            mesh=mesh_data8,
            in_specs=P("data"),
            out_specs=P(),
        )
    )
    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(out, np.full((1,), 28.0))


def test_multi_axis_collectives(mesh_2x2x2):
    def body(x):
        a = jax.lax.psum(x, "data")
        b = jax.lax.psum(a, "model")
        c = jax.lax.psum(b, "pipe")
        return c

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh_2x2x2,
            in_specs=P(("pipe", "data", "model")),
            out_specs=P(),
        )
    )
    out = f(jnp.ones(8))
    np.testing.assert_allclose(out, np.full((1,), 8.0))
