from tpu_parallel.parallel import dp, fsdp, pp, spmd, tp

__all__ = ["dp", "fsdp", "pp", "spmd", "tp"]
