"""Runtime gate: the serving daemon starts, serves, drains, exits 0.

Unlike its AST siblings this checker RUNS the product: it delegates to
``scripts/daemon_bench.py --smoke`` — one real daemon subprocess, an
HTTP submit, an SSE stream replay, SIGTERM, and the clean-journal
assertions — so ``python scripts/check_all.py`` catches a daemon that
cannot complete its own lifecycle, not just one that types wall-clock
calls in the wrong file.  It exposes the same ``check_paths() ->
[problems]`` surface the registry iterates.

Registered in ``check_all.RUNTIME_CHECKS`` (not ``CHECKERS``): the AST
gates stay instant and side-effect-free for ``tests/test_checkers.py::
test_all_ast_gates``, while this one runs as its own tier-1 entry
(``tests/test_daemon.py::test_daemon_smoke_subprocess``) and in the
``check_all`` CLI.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List, Sequence

SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))

DEFAULT_PATHS: Sequence[str] = ()  # runtime check: no tree to walk


def check_paths(paths: Sequence[str] = DEFAULT_PATHS) -> List[str]:
    spec = importlib.util.spec_from_file_location(
        "daemon_bench", os.path.join(SCRIPTS_DIR, "daemon_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    problems = [f"daemon smoke: {p}" for p in mod.run_smoke()]
    # the integrity half (PR 15): one reduced seeded disk-fault trial —
    # kill mid-stream, one-bit journal rot, restart must typed-detect
    # the damage and recover every stream bitwise
    problems += [f"disk-fault smoke: {p}" for p in mod.run_disk_smoke()]
    # the SSD-tier third (PR 18): spill a warm set through the
    # hierarchy, kill -9, warm-start from the disk manifest, and
    # replay through typed disk restores bitwise
    problems += [f"kv-disk smoke: {p}" for p in mod.run_kv_disk_smoke()]
    return problems


def main(argv: List[str]) -> int:
    problems = check_paths()
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(f"check_daemon: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("check_daemon: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
