"""Reference-parity config: tiny model, DP over 8 simulated CPU devices.

Mirrors BASELINE config 1 (the reference's data_paral.py scenario) in the
new framework's config format.
"""

from ml_collections import ConfigDict


def get_config():
    c = ConfigDict()
    c.simulate_cpu_devices = 8
    c.model = "tiny"
    c.model_overrides = ConfigDict()
    c.mesh = ConfigDict(dict(data=8, model=1, pipe=1, seq=1))
    c.global_batch_size = 32
    c.num_minibatches = 4
    c.steps = 15
    c.optimizer = "adamw"  # adamw | lion | sgd
    c.lr_schedule = "cosine"  # cosine | linear | constant
    c.ema_decay = 0.0  # >0 keeps an EMA shadow of params (eval prefers it)
    c.learning_rate = 1e-3
    c.warmup_steps = 5
    c.weight_decay = 0.01
    c.grad_clip = 1.0
    c.seed = 69
    c.log_every = 5
    c.donate = True
    # optional run plumbing (empty = disabled)
    c.checkpoint_dir = ""
    c.checkpoint_every = 100
    c.data_path = ""
    c.data_format = "flat"  # flat | packed (EOS-delimited docs + segment_ids)
    c.eos_id = 50256
    c.eval_steps = 0
    c.eval_every = 0  # >0: periodic eval during fit (uses the held-out split)
    c.keep_best = False  # snapshot lowest-eval-loss state to {checkpoint_dir}/best
    return c