"""Decoder-only transformer LM, composable over DP x FSDP x TP x PP meshes.

The flagship model family for the BASELINE.json matrix: GPT-2 125M/350M
(learned positions, LayerNorm, gelu) and Llama-style (RoPE, RMSNorm, SwiGLU)
via :class:`~tpu_parallel.models.layers.TransformerConfig` switches.  No
reference model exists to mirror (the reference trains 2-layer MLPs only);
the parallelism semantics follow the framework's strategy modules:

- TP: structural (TPDense everywhere; identity on tp=1 meshes).
- FSDP: ``config.fsdp`` wraps each Block / embedding in
  ``fsdp.shard_module_params`` over the data axis — gathers are per-block,
  so peak HBM holds one block's full weights, not the model's.
- PP: ``pipe_size > 1`` runs the block stack as GPipe stages over the pipe
  axis.  Logits are then valid on the **last** pipe rank only — train with
  :func:`make_gpt_loss`, which masks by :func:`pp.last_stage_mask`.
  ``positions``/``segment_ids`` (packed sequences) ride as pipeline extras:
  each rank indexes its current microbatch's slice of the replicated
  arrays — no extra ring traffic.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.core.losses import token_cross_entropy
from tpu_parallel.core.metrics import Metrics
from tpu_parallel.core.rng import fold_rng_over_axis
from tpu_parallel.models.layers import (
    Attention,
    Block,
    BlockStack,
    Embedding,
    RelativePositionBias,
    TransformerConfig,
    make_norm,
)
from tpu_parallel.parallel import fsdp, pp
from tpu_parallel.parallel.tp import TPDense


@dataclasses.dataclass(frozen=True)
class GPTConfig(TransformerConfig):
    """TransformerConfig plus pipeline degree (static model knobs only)."""

    pipe_size: int = 1  # number of pipeline stages the block stack is cut into
    # virtual stages per pipe rank (circular schedule).  >1 cuts the GPipe
    # bubble ~interleave-fold: rank r holds layer chunks r, r+pipe,
    # r+2*pipe, ... and activations lap the ring `interleave` times.  Not
    # yet composable with MoE (nn.switch requires identical variable
    # writes across branches; each chunk sows its own balance loss).
    pipe_interleave: int = 1
    # pipeline TRAINING schedule: "gpipe" differentiates through the full
    # microbatch schedule (activation memory grows with num_microbatches);
    # "1f1b" computes gradients inside a one-forward-one-backward schedule
    # that bounds in-flight microbatches at pipe_size per rank (see
    # parallel/pp.py pipeline_1f1b_grads) at the cost of ~pipe_size extra
    # bubble ticks.  Same math (grad-parity pinned in tests/test_pp.py);
    # forward/eval/serving always run the GPipe/ring paths.  Not yet
    # composable with pipe_interleave > 1 or MoE.
    pipe_schedule: str = "gpipe"
    # chunked lm_head + CE: compute logits ``loss_chunk`` sequence positions
    # at a time inside the loss (rematerialized in the backward), so the full
    # [B, S, vocab] logits tensor never exists in HBM.  0 = off.  The
    # dominant-memory fix for large batches at GPT-2 vocab (50304): full
    # logits are ~3 GB bf16 per 32x1024 batch, twice that with their
    # gradient.  Costs one extra lm_head matmul in the backward (~9% of
    # model FLOPs) — a win whenever it unlocks a larger batch.
    loss_chunk: int = 0


def _make_lm_head(
    cfg: "GPTConfig",
    name: Optional[str] = "lm_head",
    gather: bool = True,
    fsdp_wrap: bool = True,
):
    """The vocab projection — one definition for the in-model call and the
    standalone apply in :func:`make_gpt_loss` (``name=None``; the loss binds
    it directly to ``params["lm_head"]``).  The loss path passes
    ``gather=False``: logits stay column-sharded over the model axis and CE
    runs vocab-parallel (``core.losses.vocab_parallel_cross_entropy``) —
    the public model surface keeps full-vocab logits for generation/interop.
    The parameter tree is identical either way.

    Under ``cfg.fsdp`` the head is FSDP-wrapped like the blocks (the vocab
    kernel is among the largest single params in the model).  Callers that
    apply the head repeatedly in a scan (chunked CE, the decode loop) pass
    ``fsdp_wrap=False`` and pre-gather via :func:`_lm_head_params` ONCE
    outside the loop — the wrapped module would re-all_gather the kernel
    every iteration (jax.checkpoint pins the gather inside the scan body, so
    XLA cannot hoist it)."""
    cls = fsdp.maybe_shard(TPDense, cfg) if fsdp_wrap else TPDense
    return cls(
        features=cfg.vocab_size,
        axis_name=cfg.model_axis,
        style="column",
        gather_output=gather,
        use_bias=False,
        dtype=cfg.dtype,
        name=name,
    )


def _lm_head_params(cfg: "GPTConfig", params):
    """The lm_head param subtree, FSDP-gathered ONCE when sharded.

    Pairs with ``_make_lm_head(..., fsdp_wrap=False)``: the returned tree is
    the full (per-TP-rank) weight, safe to close over in a chunk/decode scan
    without re-gathering per iteration.  The gather's custom backward still
    psum_scatters the accumulated cotangent, so gradients are identical to
    the per-iteration-gather form.  No-op when the data axis is unbound
    (plain ``generate`` on exported params) or ``cfg.fsdp`` is off."""
    from tpu_parallel.parallel.tp import axis_size_or_none

    lm = params["lm_head"]
    if cfg.fsdp and axis_size_or_none(cfg.data_axis) is not None:
        lm = fsdp.gather_params(lm, cfg.data_axis)
    return lm


class GPTLM(nn.Module):
    """tokens [B, S] -> logits [B, S, vocab].

    ``positions`` contract under ``positional="relative"``: every row must
    hold the SAME position vector (the bias table is computed once from row
    0 — ragged/packed per-row positions are refused by the framework entry
    points, and a direct ``apply`` with genuinely per-row positions would
    silently get row-0 bias for all rows).  Learned/rope positional modes
    accept per-row positions.
    """

    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        positions: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
        hidden_only: bool = False,
        write_index: Optional[jax.Array] = None,
        block_table: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        if write_index is not None and not decode:
            raise ValueError(
                "write_index (slot-indexed cache writes) requires decode=True"
            )
        if block_table is not None and cfg.kv_block_tokens < 1:
            raise ValueError(
                "block_table passed but kv_block_tokens == 0 — paged KV "
                "serving requires a model built with kv_block_tokens/"
                "kv_pool_blocks (the serving engine constructs one)"
            )
        if write_index is not None and cfg.positional == "relative":
            # the shared T5 bias table is computed from ROW 0's positions
            # (for_step below); a slot pool holds rows at different depths,
            # so every other row would silently get row-0's bias — refuse
            # loudly instead (serve relative-bias models through generate())
            raise NotImplementedError(
                "slot-indexed cache writes with relative position bias "
                "(the shared bias table assumes row-uniform positions; "
                "slot-pool rows sit at different depths)"
            )
        if decode and positions is None:
            # default decode positions from a model-level step counter, so
            # learned positional embeddings see global positions (Attention
            # keeps its own per-layer cache index for the K/V mask — both
            # advance by the same token count and stay consistent)
            counter = self.variable(
                "cache", "decode_pos", lambda: jnp.zeros((), jnp.int32)
            )
            positions = jnp.broadcast_to(
                counter.value + jnp.arange(tokens.shape[1])[None, :], tokens.shape
            )
            counter.value = counter.value + tokens.shape[1]
        x = fsdp.maybe_shard(Embedding, cfg)(cfg, name="embed")(
            tokens, positions=positions
        )

        attn_bias = None
        if cfg.positional == "relative":
            # T5-style bucketed score bias, ONE table shared by every layer
            # (hence computed here, above the stack) — xla attention path
            # only; PP would need the bias as a pipeline extra and packing
            # per-row position tables, neither wired yet
            if cfg.pipe_size > 1:
                raise NotImplementedError(
                    "relative position bias under pipeline parallelism"
                )
            if cfg.attn_impl != "xla":
                raise NotImplementedError(
                    "relative position bias needs attn_impl='xla' (the "
                    "flash/ring/ulysses kernels take no additive score bias)"
                )
            if segment_ids is not None:
                raise NotImplementedError(
                    "relative position bias with packed sequences"
                )
            attn_bias = RelativePositionBias(
                cfg, bidirectional=cfg.bidirectional, name="rel_bias"
            ).for_step(positions, tokens.shape[1], cfg.seq_len, decode)

        if cfg.pipe_interleave > 1 and cfg.pipe_size <= 1:
            raise ValueError(
                "pipe_interleave > 1 requires pipe_size > 1 (a pipe mesh "
                "axis); on a pipe=1 mesh the knob would be silently ignored"
            )
        if cfg.pipe_size > 1:
            if write_index is not None or block_table is not None:
                raise NotImplementedError(
                    "slot-indexed cache writes under pipeline parallelism "
                    "(the decode ring's per-stage caches would need the "
                    "write-slot table as a ring extra — serve pipe meshes "
                    "through generate_sharded, not the serving engine)"
                )
            chunks = cfg.pipe_size * cfg.pipe_interleave
            if cfg.n_layers % chunks != 0:
                raise ValueError(
                    f"n_layers={cfg.n_layers} not divisible by pipe_size*"
                    f"pipe_interleave={chunks}"
                )
            if cfg.pipe_interleave > 1 and cfg.moe_experts > 0:
                raise NotImplementedError(
                    "MoE under the interleaved pipeline schedule (chunk "
                    "branches would sow mismatched loss collections)"
                )
            layers_per_chunk = cfg.n_layers // chunks
            if cfg.moe_experts > 0 and cfg.moe_dispatch == "alltoall":
                from tpu_parallel.core.metrics import pvary_missing
                from tpu_parallel.parallel.tp import axis_size_or_none

                if axis_size_or_none(cfg.model_axis) is not None:
                    # the a2a MoE's closing all_gather makes stage outputs
                    # model-VARYING; the pipeline scan's activation carry
                    # must enter that way or the carry types disagree
                    # (same rule as BlockStack's inner scan)
                    x = pvary_missing(x, (cfg.model_axis,))
            pipeline = pp.PipelineModule(
                stage_fn=functools.partial(BlockStack, cfg, layers_per_chunk),
                num_microbatches=cfg.num_microbatches,
                axis_name=cfg.pipe_axis,
                # BlockStack accepts aux_scale: bubble ticks contribute
                # exactly zero to sown losses (MoE balance)
                pass_validity=True,
                interleave=cfg.pipe_interleave,
                name="pipeline",
            )
            if decode:
                from tpu_parallel.parallel.tp import axis_size_or_none

                if segment_ids is not None:
                    # mirror the non-PP decode refusal (Attention raises) —
                    # silently dropping them would attend across documents
                    raise NotImplementedError(
                        "incremental decoding with packed sequences "
                        "(segment_ids)"
                    )
                if axis_size_or_none(cfg.pipe_axis) is None:
                    # fail clearly here — otherwise the ring's collectives
                    # die on an unbound-axis error deep in JAX
                    raise ValueError(
                        f"pipe_size={cfg.pipe_size} decoding needs the "
                        f"{cfg.pipe_axis!r} mesh axis bound: serve through "
                        "generate_sharded under the training mesh (plain "
                        "generate()/generate_beam() run without a mesh)"
                    )
                # ring decode (pp.execute_pipeline_decode): positions ride
                # through directly — no scan, so traced kwargs are fine
                x = pipeline(x, train=train, decode=True, positions=positions)
            else:
                # packed sequences / explicit positions ride as pipeline
                # extras: every rank holds them replicated and indexes its
                # current microbatch locally (pp.execute_pipeline_step)
                extras = {}
                if segment_ids is not None:
                    extras["segment_ids"] = segment_ids
                if positions is not None:
                    extras["positions"] = positions
                x = pipeline(x, train=train, extras=extras or None)
        else:
            x = BlockStack(cfg, cfg.n_layers, name="blocks")(
                x,
                positions=positions,
                segment_ids=segment_ids,
                train=train,
                decode=decode,
                attn_bias=attn_bias,
                write_index=write_index,
                block_table=block_table,
            )

        if cfg.prenorm:
            # post-norm stacks (BERT interop) leave the trunk already
            # normalized by the last block's norm_mlp — an extra final norm
            # has no HF counterpart and would break checkpoint parity
            x = make_norm(cfg, "norm_final")(x).astype(cfg.dtype)
        if hidden_only:
            # for chunked-loss training (make_gpt_loss applies the lm_head
            # itself, loss_chunk positions at a time)
            return x
        # Logits stay in cfg.dtype: the bf16 matmul already rounded them, so
        # an fp32 cast here would only double the largest tensor in the
        # program (see token_cross_entropy, which upcasts inside the
        # reductions instead).
        return _make_lm_head(cfg)(x)


def make_ce_fn(config: GPTConfig):
    """``(lm_params, hidden, targets, mask) -> (loss_sum, correct_sum)``:
    the shared CE machinery of every token-prediction objective (causal LM,
    MLM, seq2seq) — vocab-parallel under TP, sequence-chunked under
    ``config.loss_chunk``.

    ``lm_params`` must be pre-gathered when FSDP-sharded
    (:func:`_lm_head_params`): the head applied here is unwrapped, so the
    chunk scan never re-all_gathers the vocab kernel per iteration."""
    from tpu_parallel.core.losses import vocab_parallel_cross_entropy

    chunk = config.loss_chunk
    head = _make_lm_head(config, name=None, gather=False, fsdp_wrap=False)

    def ce_block(lm_params, h, targets, mask):
        """lm_head + CE + accuracy on one block of hidden states; returns
        (loss_sum, correct_sum).  Vocab-parallel when the model axis is
        bound (mesh path), plain CE on full logits otherwise."""
        from tpu_parallel.parallel.tp import axis_size_or_none

        logits = head.apply({"params": lm_params}, h)
        if axis_size_or_none(config.model_axis) is not None:
            ce, pred = vocab_parallel_cross_entropy(
                logits, targets, config.model_axis
            )
        else:
            ce = token_cross_entropy(logits, targets)
            pred = logits.argmax(-1)
        loss_sum = (ce * mask).sum()
        correct = ((pred == targets) * mask).sum()
        return loss_sum, correct

    def chunked_ce(lm_params, h, targets, mask):
        """scan ce_block over sequence chunks; logits exist only
        [B, loss_chunk, vocab/tp] at a time."""
        b, s = targets.shape
        if s % chunk != 0:
            raise ValueError(f"seq_len={s} not divisible by loss_chunk={chunk}")
        n = s // chunk
        hs = h.reshape(b, n, chunk, h.shape[-1]).transpose(1, 0, 2, 3)
        ts = targets.reshape(b, n, chunk).transpose(1, 0, 2)
        ms = mask.reshape(b, n, chunk).transpose(1, 0, 2)

        def body(carry, xs):
            loss_sum, correct = ce_block(lm_params, *xs)
            return (carry[0] + loss_sum, carry[1] + correct), None

        # promote the zero carry to the body outputs' varying-axes type (the
        # hidden states' axes plus the model axis, which the CE's psums over
        # the sharded vocab introduce) so the scan type-checks under
        # shard_map's replication checker
        from tpu_parallel.core.metrics import pvary_missing, vma_of

        vma = vma_of(h)
        if vma and config.model_axis not in vma:
            vma = vma + (config.model_axis,)
        init = (
            pvary_missing(jnp.float32(0.0), vma),
            pvary_missing(jnp.float32(0.0), vma),
        )
        (loss_sum, correct), _ = lax.scan(jax.checkpoint(body), init, (hs, ts, ms))
        return loss_sum, correct

    return chunked_ce if chunk else ce_block


def make_gpt_1f1b_grad_fn(config: GPTConfig, train: bool = True):
    """``(params, batch, rng) -> (grads, metrics)`` via the memory-bounded
    1F1B pipeline schedule (:func:`tpu_parallel.parallel.pp.pipeline_1f1b_grads`).

    Replaces the ``jax.grad``-through-GPipe path inside the train step when
    ``config.pipe_schedule == "1f1b"``: in-flight microbatch activations are
    bounded at ``pipe_size`` per rank instead of ``num_microbatches``.  The
    forward/eval/serving paths (``GPTLM.__call__``) are untouched — the
    schedule only changes HOW gradients are computed, not the math: grads
    and loss match the GPipe step (tests/test_pp.py pins parity).

    The per-rank composite mirrors ``GPTLM``'s pipe path module-by-module
    and BY NAME (embed / pipeline.stage / norm_final / lm_head), so the
    params tree initialized through the standard path serves unchanged.
    """
    # pipe_size == 1 is the legitimate degenerate: every tick forwards and
    # immediately backwards one microbatch — per-microbatch vjp
    # accumulation, the n=1 baseline of the scaling harness
    if config.pipe_interleave > 1:
        raise NotImplementedError(
            "1F1B with interleaved virtual stages (the circular schedule's "
            "chunk walk and the 1F1B buffer discipline do not compose yet)"
        )
    if config.moe_experts > 0:
        raise NotImplementedError(
            "MoE under 1F1B (sown balance losses need per-tick replay "
            "bookkeeping the schedule does not carry)"
        )
    if config.positional == "relative":
        raise NotImplementedError("relative position bias under pipelines")
    layers_per_stage = config.n_layers // config.pipe_size
    if config.n_layers % config.pipe_size:
        raise ValueError(
            f"n_layers={config.n_layers} not divisible by "
            f"pipe_size={config.pipe_size}"
        )

    from tpu_parallel.parallel.tp import ModuleShard

    ce_fn = make_ce_fn(config)
    embed_mod = fsdp.maybe_shard(Embedding, config)(config)
    if config.pipe_size > 1:
        stage_mod = ModuleShard(
            module_fn=functools.partial(BlockStack, config, layers_per_stage),
            axis_name=config.pipe_axis,
        )
        stage_params = lambda p: p["pipeline"]["stage"]  # noqa: E731
    else:
        # degenerate single-stage: GPTLM builds a plain BlockStack named
        # "blocks" at pipe_size=1 — mirror that tree
        stage_mod = BlockStack(config, config.n_layers)
        stage_params = lambda p: p["blocks"]  # noqa: E731
    norm_mod = make_norm(config, None) if config.prenorm else None
    fold_axes = (
        config.data_axis, config.model_axis, config.pipe_axis, config.seq_axis
    )

    def fwd_fn(params, x_in, mb, rng_mb):
        dropout_rng = fold_rng_over_axis(rng_mb, fold_axes)
        x0 = embed_mod.apply(
            {"params": params["embed"]}, mb.tokens, positions=mb.positions
        )
        stage_idx = lax.axis_index(config.pipe_axis)
        x = jnp.where(stage_idx == 0, x0, x_in)
        y = stage_mod.apply(
            {"params": stage_params(params)},
            x,
            positions=mb.positions,
            segment_ids=mb.segment_ids,
            train=train,
            rngs={"dropout": dropout_rng},
        )
        h = y
        if norm_mod is not None:
            h = norm_mod.apply({"params": params["norm_final"]}, y).astype(
                config.dtype
            )
        mask = (
            mb.loss_mask
            if mb.loss_mask is not None
            else jnp.ones(mb.targets.shape, jnp.float32)
        )
        mask = mask * pp.last_stage_mask(config.pipe_axis)
        n_tok = mask.sum()
        loss_sum, correct = ce_fn(
            _lm_head_params(config, params), h, mb.targets, mask
        )
        metrics: Metrics = {
            "loss": (loss_sum, n_tok),
            "accuracy": (correct.astype(jnp.float32), n_tok),
        }
        return y, loss_sum, metrics

    def grad_fn(params, batch, rng):
        mb_rows = batch.tokens.shape[0] // config.num_microbatches
        return pp.pipeline_1f1b_grads(
            fwd_fn,
            params,
            batch,
            rng,
            num_microbatches=config.num_microbatches,
            axis_name=config.pipe_axis,
            act_shape=(mb_rows, batch.tokens.shape[1], config.d_model),
            act_dtype=config.dtype,
        )

    return grad_fn


def make_gpt_loss(config: GPTConfig, train: bool = True):
    """Next-token CE in the accumulate_gradients loss shape, PP/TP-aware.

    Dropout RNG folds over every parallel axis; under PP the loss and metric
    counts are masked to the last pipe rank (the only rank with real logits).
    ``train=False`` builds the evaluation variant (dropout off).

    The lm_head is applied here, not in the model: logits stay column-
    sharded over the model axis and CE runs vocab-parallel — under TP the
    full-vocab [B, S, vocab] logits tensor never materializes and the
    per-microbatch all_gather (the largest TP collective) disappears;
    the softmax statistics cost three O(B*S) scalar collectives instead.

    With ``config.loss_chunk > 0`` the lm_head + CE additionally run
    ``loss_chunk`` sequence positions at a time under a rematerialized
    ``lax.scan`` — even the vocab-*sharded* logits never exist at full
    sequence length (see ``GPTConfig.loss_chunk``).
    """
    fold_axes = (
        config.data_axis, config.model_axis, config.pipe_axis, config.seq_axis
    )
    ce_fn = make_ce_fn(config)

    def loss_fn(params, apply_fn, batch, rng):
        dropout_rng = fold_rng_over_axis(rng, fold_axes)
        apply_kwargs = dict(
            positions=batch.positions,
            segment_ids=batch.segment_ids,
            train=train,
            rngs={"dropout": dropout_rng},
            hidden_only=True,
        )
        aux_loss = 0.0
        if config.moe_experts > 0:
            hidden, mods = apply_fn(
                {"params": params}, batch.tokens, mutable=["losses"], **apply_kwargs
            )
            sown = jax.tree_util.tree_leaves(mods.get("losses", {}))
            if sown:
                # Normalize the tick/layer-stacked sum so the aux gradient per
                # router matches the no-PP case regardless of pipe degree.
                # Without PP each of the n_layers blocks sows once.  Under PP
                # each rank's layers_per_stage blocks sow once per REAL tick
                # (bubble ticks zeroed via aux_scale — pp.py), i.e.
                # num_microbatches times — and every rank adds its own
                # aux term to its local total, so the denominator must count
                # ALL layers (n_layers, not layers_per_stage): summed across
                # ranks the aux terms then reconstruct exactly the per-layer
                # mean-over-microbatches, and each router's gradient carries
                # the same 1/n_layers weight as at pipe_size=1
                # (tests/test_moe.py::test_pp_aux_gradient_invariance).
                if config.pipe_size > 1:
                    denom = config.n_layers * config.num_microbatches
                else:
                    denom = config.n_layers
                aux_loss = sum(jnp.sum(leaf) for leaf in sown) / denom
        else:
            hidden = apply_fn({"params": params}, batch.tokens, **apply_kwargs)
        mask = (
            batch.loss_mask
            if batch.loss_mask is not None
            else jnp.ones(batch.targets.shape, jnp.float32)
        )
        if config.pipe_size > 1:
            mask = mask * pp.last_stage_mask(config.pipe_axis)
        n_tok = mask.sum()
        loss_sum, correct = ce_fn(
            _lm_head_params(config, params), hidden, batch.targets, mask
        )
        metrics: Metrics = {
            "loss": (loss_sum, n_tok),
            "accuracy": (correct.astype(jnp.float32), n_tok),
        }
        total = loss_sum / jnp.maximum(n_tok, 1.0)
        if config.moe_experts > 0:
            # Metric: the full-model per-layer balance mean.  Under PP each
            # rank holds only its stage's share (aux_loss sums to the full
            # mean across ranks) and n_tok is nonzero on the last rank only —
            # psum the shares so the reported value covers every layer.
            aux_metric = aux_loss
            if config.pipe_size > 1:
                aux_metric = lax.psum(aux_loss, config.pipe_axis)
            metrics["moe_balance"] = (aux_metric * n_tok, n_tok)
            total = total + config.moe_balance_weight * aux_loss
        return total, metrics

    return loss_fn


class EncoderClassifier(nn.Module):
    """Sequence classification head over the (bidirectional) trunk.

    The BERT fine-tune shape: encoder hidden states -> pooled vector
    (``"first"`` = CLS-style first token through a tanh pooler, ``"mean"``
    = mean over the row's FIRST segment when ``segment_ids`` are given —
    padding/foreign segments excluded — else over every position) -> class
    logits.  Works with
    :func:`~tpu_parallel.core.losses.make_classification_loss` unchanged
    (``apply_fn(tokens)`` -> ``[batch, num_classes]``); the trunk composes
    with TP/FSDP exactly as the LM does.  Requires ``bidirectional=True``:
    under a causal mask the CLS position attends to nothing but itself.
    """

    config: GPTConfig
    num_classes: int
    pool: str = "first"  # "first" (CLS) | "mean"

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        positions: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        train: bool = True,
    ) -> jax.Array:
        cfg = self.config
        if not cfg.bidirectional:
            raise ValueError(
                "EncoderClassifier requires bidirectional=True — under a "
                "causal mask the pooled position cannot see the sequence"
            )
        h = GPTLM(cfg, name="encoder")(
            tokens,
            positions=positions,
            segment_ids=segment_ids,
            train=train,
            hidden_only=True,
        )
        if self.pool == "mean":
            if segment_ids is not None:
                # pool only the row's first segment: pad tokens (and any
                # packed neighbours) must not shift the pooled vector
                w = (segment_ids == segment_ids[:, :1]).astype(h.dtype)[..., None]
                pooled = (h * w).sum(axis=1) / jnp.maximum(w.sum(axis=1), 1.0)
            else:
                pooled = h.mean(axis=1)
        elif self.pool == "first":
            pooled = h[:, 0]
        else:
            raise ValueError(f"pool={self.pool!r} (first | mean)")
        pooled = jnp.tanh(
            nn.Dense(cfg.d_model, dtype=cfg.dtype, name="pooler")(pooled)
        )
        if cfg.dropout_rate > 0.0:
            pooled = nn.Dropout(
                rate=cfg.dropout_rate, deterministic=not train
            )(pooled)
        # fp32 class logits: tiny tensor, and the CE upcast costs nothing
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, name="classifier"
        )(pooled)


def make_mlm_loss(
    config: GPTConfig,
    mask_rate: float = 0.15,
    mask_token_id: Optional[int] = None,
    train: bool = True,
):
    """Masked-LM objective for bidirectional (encoder) configs.

    Wraps :func:`make_gpt_loss`'s CE machinery (vocab-parallel under TP,
    chunked under ``loss_chunk``, PP-masked): each step corrupts
    ``mask_rate`` of the input tokens to ``mask_token_id`` (default: the
    last vocab id, by convention reserved for [MASK]) and scores the model
    on recovering the originals at exactly those positions.

    RNG discipline: the corruption pattern folds over the data and seq axes
    only — model/pipe ranks hold replicated copies of the same tokens and
    MUST corrupt them identically, while data/seq shards draw independent
    masks.  (Dropout keeps its own all-axes fold inside the inner loss.)
    """
    from tpu_parallel.core.state import TextBatch

    inner = make_gpt_loss(config, train=train)
    mask_id = (
        mask_token_id if mask_token_id is not None else config.vocab_size - 1
    )
    corrupt_axes = (config.data_axis, config.seq_axis)

    def loss_fn(params, apply_fn, batch, rng):
        mask_rng = fold_rng_over_axis(jax.random.fold_in(rng, 17), corrupt_axes)
        masked = jax.random.bernoulli(mask_rng, mask_rate, batch.tokens.shape)
        corrupted = jnp.where(masked, mask_id, batch.tokens)
        loss_mask = masked.astype(jnp.float32)
        if batch.loss_mask is not None:
            loss_mask = loss_mask * batch.loss_mask
        mlm_batch = TextBatch(
            tokens=corrupted,
            targets=batch.tokens,
            loss_mask=loss_mask,
            positions=batch.positions,
            segment_ids=batch.segment_ids,
        )
        return inner(params, apply_fn, mlm_batch, rng)

    return loss_fn


# --- Named configurations (BASELINE.md matrix) --------------------------------


def gpt2_125m(**overrides) -> GPTConfig:
    return GPTConfig(
        **{
            **dict(
                vocab_size=50304, d_model=768, n_layers=12, n_heads=12, seq_len=1024
            ),
            **overrides,
        }
    )


def gpt2_350m(**overrides) -> GPTConfig:
    return GPTConfig(
        **{
            **dict(
                vocab_size=50304, d_model=1024, n_layers=24, n_heads=16, seq_len=1024
            ),
            **overrides,
        }
    )


def llama_1b(**overrides) -> GPTConfig:
    return GPTConfig(
        **{
            **dict(
                vocab_size=32000,
                d_model=2048,
                n_layers=16,
                n_heads=16,
                seq_len=2048,
                positional="rope",
                norm="rmsnorm",
                mlp="swiglu",
            ),
            **overrides,
        }
    )


def bert_base(**overrides) -> GPTConfig:
    """BERT-base-shaped bidirectional encoder (MLM via make_mlm_loss).

    vocab 30522 padded to 30592 (multiple of 128 for MXU lanes; the last id
    doubles as [MASK] by make_mlm_loss's default).
    """
    return GPTConfig(
        **{
            **dict(
                vocab_size=30592,
                d_model=768,
                n_layers=12,
                n_heads=12,
                seq_len=512,
                bidirectional=True,
            ),
            **overrides,
        }
    )


def bert_base_hf(**overrides) -> GPTConfig:
    """BERT-base in its ORIGINAL (HF-checkpoint-faithful) form: post-norm
    residuals, embeddings.LayerNorm, erf gelu, vocab 30522 unpadded —
    the config :func:`~tpu_parallel.models.hf.from_hf_bert` imports into.
    For from-scratch pretraining prefer :func:`bert_base` (pre-norm,
    MXU-padded vocab)."""
    return GPTConfig(
        **{
            **dict(
                vocab_size=30522,
                d_model=768,
                n_layers=12,
                n_heads=12,
                seq_len=512,
                bidirectional=True,
                prenorm=False,
                embed_norm=True,
                mlp="gelu_exact",
                scan_layers=False,
                # BERT's LayerNorm epsilon (GPT-2/Llama use 1e-5; with the
                # wrong eps all 25 norms silently drift from torch)
                norm_eps=1e-12,
            ),
            **overrides,
        }
    )


def tiny_test(**overrides) -> GPTConfig:
    """Small config for CPU-mesh tests: real structure, toy sizes."""
    return GPTConfig(
        **{
            **dict(
                vocab_size=256,
                d_model=32,
                n_layers=4,
                n_heads=4,
                seq_len=32,
                dtype=jnp.float32,
                num_microbatches=2,
            ),
            **overrides,
        }
    )
