"""Collect N processes' span logs and stitch ONE Perfetto timeline.

The fleet's tracing story ends here: every process spooled its spans
locally (``tpu_parallel/obs/spool.py``, served at ``GET /v1/tracez``),
and this CLI gathers those per-process views — from span-log FILES on
disk, from live ``/v1/tracez`` ENDPOINTS, or both — rebases them onto
the router's clock via the spooled ``clock_sync`` samples, and writes
one Chrome/Perfetto trace-event JSON with one pid per process and flow
arrows across every wire crossing (``tpu_parallel/obs/stitch.py`` does
the math; docs/11_observability.md tells the story).

Usage::

    python scripts/trace_stitch.py out.json LOG[=ADDR] ... \
        [--url HOST:PORT ...] [--trace-id ID] [--summary]

- ``LOG[=ADDR]`` — a span-log JSONL file; the optional ``=ADDR`` names
  the ``host:port`` the router knows this process by, which is how its
  records join the router's ``clock_sync`` samples for EXACT alignment
  (without it, the stitcher falls back to earliest-record alignment).
- ``--url HOST:PORT`` — fetch ``http://HOST:PORT/v1/tracez`` live; the
  address doubles as the clock-alignment key.
- ``--trace-id ID`` — filter every source to one trace.
- ``--summary`` — also print the per-trace verdict (span count, pids,
  single-rootedness, cross-process links) as JSON on stdout.

Exit status is nonzero when no records were collected — an empty
stitch is a misconfiguration, not a timeline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tpu_parallel.obs.spool import read_span_log  # noqa: E402
from tpu_parallel.obs.stitch import (  # noqa: E402
    stitch_traces,
    trace_summary,
)


def _proc_from_log(path: str, addr: Optional[str],
                   trace_id: Optional[str]) -> Dict:
    """One stitchable process view from a span-log file.  Name and pid
    come from the log's own meta record — the process stamped them."""
    records, skipped = read_span_log(path, trace_id=trace_id)
    meta = next((r for r in records if r.get("kind") == "meta"), {})
    proc = {
        "name": meta.get("proc", path),
        "pid": meta.get("pid", 0),
        "records": records,
        "skipped": skipped,
    }
    if addr:
        proc["addr"] = addr
    return proc


def _proc_from_url(addr: str, trace_id: Optional[str],
                   timeout: float) -> Dict:
    """One stitchable process view from a live ``/v1/tracez``."""
    query = (
        f"?trace_id={urllib.parse.quote(trace_id, safe='')}"
        if trace_id else ""
    )
    url = f"http://{addr}/v1/tracez{query}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        payload = json.loads(resp.read())
    return {
        "name": payload.get("proc", addr),
        "pid": payload.get("pid", 0),
        "addr": addr,
        "records": payload.get("records", []),
        "skipped": payload.get("skipped", {}),
    }


def collect(
    logs: List[str],
    urls: List[str],
    trace_id: Optional[str] = None,
    timeout: float = 10.0,
) -> List[Dict]:
    """Gather every named source into stitch_traces' input shape.  A
    file that does not exist yields an empty view (read_span_log's
    contract); an unreachable URL is a hard error — the operator named
    a live endpoint and should hear that it is not one."""
    processes: List[Dict] = []
    for spec in logs:
        path, _, addr = spec.partition("=")
        processes.append(_proc_from_log(path, addr or None, trace_id))
    for addr in urls:
        try:
            processes.append(_proc_from_url(addr, trace_id, timeout))
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise SystemExit(f"trace_stitch: {addr}/v1/tracez: {exc}")
    return processes


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_stitch",
        description="stitch N span logs into one Perfetto trace",
    )
    ap.add_argument("out", help="output trace-event JSON path")
    ap.add_argument(
        "logs", nargs="*",
        help="span-log files, each optionally LOG=ADDR for clock "
             "alignment against the router's clock_sync samples",
    )
    ap.add_argument(
        "--url", action="append", default=[], metavar="HOST:PORT",
        help="fetch a live /v1/tracez (repeatable)",
    )
    ap.add_argument("--trace-id", default=None)
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument(
        "--summary", action="store_true",
        help="print the per-trace verdict JSON on stdout",
    )
    args = ap.parse_args(argv[1:])
    if not args.logs and not args.url:
        ap.error("need at least one span log or --url")

    processes = collect(
        args.logs, args.url, trace_id=args.trace_id,
        timeout=args.timeout,
    )
    total = sum(len(p["records"]) for p in processes)
    if total == 0:
        print("trace_stitch: no records collected", file=sys.stderr)
        return 1
    trace = stitch_traces(processes)
    with open(args.out, "w") as fh:
        json.dump(trace, fh)
    summary = trace_summary(processes)
    print(
        f"trace_stitch: {len(processes)} process(es), {total} records, "
        f"{len(trace['traceEvents'])} events, "
        f"{trace['metadata']['flow_arrows']} flow arrow(s), "
        f"{len(summary)} trace(s) -> {args.out}",
        file=sys.stderr,
    )
    if args.summary:
        json.dump(summary, sys.stdout, indent=2)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
