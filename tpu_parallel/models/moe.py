"""Mixture-of-Experts MLP with expert parallelism (top-k routing).

``moe_top_k=1`` is Switch (gate = raw router probability); ``>1`` is
GShard-style with gates renormalized over the chosen experts and capacity
claimed choice-major under the same static-shape dispatch.

No reference capability exists (SURVEY.md §2.2: EP "Absent"); built for the
framework's EP slot, TPU-first:

- **Static shapes everywhere**: capacity-based routing (``capacity_factor``)
  with one-hot dispatch/combine einsums — the Mesh-TensorFlow/Switch
  formulation that XLA compiles to dense MXU work, no dynamic gather.
- **Expert parallelism over the ``model`` mesh axis**: each rank owns
  ``n_experts / ep`` experts (weights stacked per-rank via ModuleShard, so
  gradient sync already treats them as partitioned).  Activations are
  replicated over the model axis (the batch shards over data/seq), so
  dispatch needs **no communication at all**: each rank slices out its own
  experts' dispatch/combine masks, runs only its experts (``1/ep`` of the
  expert FLOPs), and the partial combines close with one ``psum`` — the
  same collective shape as a TP row-parallel projection, so the existing
  pmean-over-model gradient sync stays exact.
- **Router in fp32** (numerically fragile softmax over experts), activations
  in the model dtype.
- Load-balance auxiliary loss (Switch: ``E * sum(f_i * P_i)``) sown into a
  ``"losses"`` collection; ``make_gpt_loss`` folds it into the objective.
  ``aux_scale`` gates the sown value — the pipeline schedule passes 0.0 on
  bubble ticks so garbage activations contribute exactly zero to (and take
  no gradient from) the router regularizer.

Works mesh-free too (no bound model axis): all experts live on the one
device, no slicing, no psum — same module, same params layout rules as the
rest of the structural-TP design.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.parallel.tp import ModuleShard, axis_size_or_none


class ExpertFFN(nn.Module):
    """One expert: the standard transformer FFN at model dtype.

    Projection outputs carry the same ``"proj"`` checkpoint names as the
    dense MLP (layers.py), so the proj/proj_attn remat policies save the
    expert matmuls instead of recomputing them in the backward.
    """

    config: "TransformerConfig"  # noqa: F821 — forward ref, see layers.py

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from jax.ad_checkpoint import checkpoint_name

        cfg = self.config
        hidden = cfg.mlp_ratio * cfg.d_model
        if cfg.mlp == "swiglu":
            gate = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="gate")(x)
            up = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="up")(x)
            h = nn.silu(checkpoint_name(gate, "proj")) * checkpoint_name(up, "proj")
        else:
            h = nn.gelu(
                checkpoint_name(nn.Dense(hidden, dtype=cfg.dtype, name="up")(x), "proj")
            )
        return checkpoint_name(
            nn.Dense(cfg.d_model, dtype=cfg.dtype, name="down")(h), "proj"
        )


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: top-k routed experts, EP over ``model``."""

    config: "TransformerConfig"  # noqa: F821

    @nn.compact
    def __call__(
        self, x: jax.Array, train: bool = True, aux_scale: jax.Array | None = None
    ) -> jax.Array:
        cfg = self.config
        n_experts = cfg.moe_experts
        ep_size = axis_size_or_none(cfg.model_axis) or 1
        if n_experts % ep_size != 0:
            raise ValueError(
                f"moe_experts={n_experts} not divisible by model axis {ep_size}"
            )
        local_experts = n_experts // ep_size
        b, s, d = x.shape
        tokens = b * s
        xf = x.reshape(tokens, d)

        # --- route (fp32) ---------------------------------------------------
        top_k = cfg.moe_top_k
        if not 1 <= top_k <= n_experts:
            # moe_experts=0 disables MoE entirely (dense MLP); top_k has no
            # analogous "off" value, so reject rather than silently clamp
            raise ValueError(
                f"moe_top_k={top_k} must be in [1, moe_experts={n_experts}]"
            )
        logits = nn.Dense(
            n_experts, use_bias=False, dtype=jnp.float32, name="router"
        )(xf.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

        if cfg.moe_router == "expert_choice":
            return self._expert_choice(
                x, xf, probs, aux_scale, ep_size, local_experts, train
            )
        if cfg.moe_router != "topk":
            raise ValueError(
                f"moe_router={cfg.moe_router!r} (topk | expert_choice)"
            )
        gate_vals, expert_idx = lax.top_k(probs, top_k)  # [T, k] each
        if top_k == 1:
            gates = gate_vals  # Switch: the raw router probability
        else:
            # GShard: renormalize over the chosen experts so the combined
            # output is a convex mixture regardless of how much mass the
            # un-chosen experts held
            gates = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)
        onehots = [
            jax.nn.one_hot(expert_idx[:, j], n_experts, dtype=jnp.float32)
            for j in range(top_k)
        ]

        # Load-balance loss: E * sum_i fraction_i * router_prob_i, with
        # fraction_i the share of (token, choice) assignments to expert i
        # (Switch's f_i at top_k=1).  aux_scale (0.0 on pipeline bubble
        # ticks) zeroes both the value and, through the multiply, its
        # gradient into the router.
        assign_frac = sum(oh.mean(axis=0) for oh in onehots) / top_k
        balance = n_experts * jnp.sum(assign_frac * probs.mean(axis=0))
        if aux_scale is not None:
            balance = balance * jnp.asarray(aux_scale, jnp.float32)
        self.sow(
            "losses",
            "moe_balance",
            balance,
            reduce_fn=lambda a, b_: a + b_,
            init_fn=lambda: jnp.float32(0.0),
        )

        # --- capacity + dispatch masks (static shapes) ----------------------
        capacity = max(
            1, int(cfg.moe_capacity_factor * top_k * tokens / n_experts + 0.999)
        )
        # choices claim capacity slots choice-major (every token's first
        # choice before any second choice), tracked by a running per-expert
        # count so the slot index stays unique across choices
        count = jnp.zeros((n_experts,), jnp.float32)
        dispatch = jnp.zeros((tokens, n_experts, capacity), jnp.float32)
        combine = jnp.zeros((tokens, n_experts, capacity), jnp.float32)
        for j, onehot in enumerate(onehots):
            position = (jnp.cumsum(onehot, axis=0) - 1.0 + count[None, :]) * onehot
            in_capacity = (position < capacity).astype(jnp.float32) * onehot
            pos_idx = jnp.sum(position, axis=-1).astype(jnp.int32)  # [T]
            pos_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
            # [T, E, C]: 1 where token t's choice j landed in slot c of expert e
            dispatch_j = in_capacity[:, :, None] * pos_onehot[:, None, :]
            dispatch = dispatch + dispatch_j
            combine = combine + dispatch_j * gates[:, j, None, None]
            count = count + jnp.sum(onehot, axis=0)

        # --- expert parallelism: slice my experts, partial-combine, psum ----
        return self._apply_experts(
            x, xf, dispatch, combine, ep_size, local_experts, train
        )

    def _expert_choice(
        self, x, xf, probs, aux_scale, ep_size, local_experts, train
    ):
        """Expert-choice routing: each expert takes its top-``capacity``
        tokens by router probability (Zhou et al., 2022).  Every expert is
        exactly full, so there is no balance loss to tune — a zero is still
        sown to keep the losses collection shape stable for the pipeline's
        bubble masking."""
        cfg = self.config
        n_experts = cfg.moe_experts
        tokens = xf.shape[0]
        capacity = max(1, int(cfg.moe_capacity_factor * tokens / n_experts + 0.999))
        if capacity > tokens:
            raise ValueError(
                f"expert capacity {capacity} > {tokens} tokens — lower "
                "moe_capacity_factor or use more tokens per batch"
            )
        # gates [E, C]: the chosen tokens' router probs; idx [E, C] token ids
        gates, idx = lax.top_k(probs.T, capacity)
        picked = jax.nn.one_hot(idx, tokens, dtype=jnp.float32)  # [E, C, T]
        dispatch = picked.transpose(2, 0, 1)  # [T, E, C]
        combine = (picked * gates[:, :, None]).transpose(2, 0, 1)

        del aux_scale  # EC has no balance loss to gate; the sown zero keeps
        # the losses collection shape stable for the pipeline bubble masking
        self.sow(
            "losses",
            "moe_balance",
            jnp.float32(0.0),
            reduce_fn=lambda a, b_: a + b_,
            init_fn=lambda: jnp.float32(0.0),
        )
        return self._apply_experts(
            x, xf, dispatch, combine, ep_size, local_experts, train
        )

    def _apply_experts(
        self, x, xf, dispatch, combine, ep_size, local_experts, train
    ):
        """Shared tail: slice my experts' masks, run the expert FFNs at
        1/ep cost, partial-combine, close with one psum."""
        cfg = self.config
        b, s, d = x.shape
        if ep_size > 1:
            rank = lax.axis_index(cfg.model_axis)
            dispatch = lax.dynamic_slice_in_dim(
                dispatch, rank * local_experts, local_experts, axis=1
            )
            combine = lax.dynamic_slice_in_dim(
                combine, rank * local_experts, local_experts, axis=1
            )

        x_exp = jnp.einsum("td,tec->ecd", xf.astype(jnp.float32), dispatch)
        x_exp = x_exp.astype(cfg.dtype)  # [E/ep, C, d]

        expert_stack = nn.vmap(
            ExpertFFN,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        if ep_size > 1:
            import functools

            y_exp = ModuleShard(
                functools.partial(expert_stack, cfg),
                axis_name=cfg.model_axis,
                name="experts",
            )(x_exp)
        else:
            y_exp = expert_stack(cfg, name="experts")(x_exp)

        # --- back to tokens -------------------------------------------------
        # Partial combine over my experts; the psum sums the disjoint expert
        # contributions (TP row-parallel shape; pmean-over-model grad sync
        # keeps upstream gradients exact, see tests/test_moe.py).
        y = jnp.einsum("ecd,tec->td", y_exp.astype(jnp.float32), combine)
        if ep_size > 1:
            with jax.named_scope("moe_combine_psum"):
                y = lax.psum(y, cfg.model_axis)
        y = y.astype(cfg.dtype).reshape(b, s, d)
        if cfg.dropout_rate > 0.0:
            y = nn.Dropout(rate=cfg.dropout_rate, deterministic=not train)(y)
        return y
