"""Data-parallel integration tests on the 8-device CPU mesh.

Covers the reference's implicit smoke test ("loss goes down on 8 fake
devices", ``data_paral.py:255-277``) plus the numerical test the reference
never had: DP on N devices == single-device training on the same global batch.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import Batch, TrainState, compute
from tpu_parallel.core.losses import make_classification_loss
from tpu_parallel.data import classification_batch
from tpu_parallel.models import MLPClassifier, MLPConfig
from tpu_parallel.parallel import dp
from tpu_parallel.runtime import MeshConfig, make_mesh

CFG = MLPConfig(hidden_size=64, num_classes=10, dropout_rate=0.0)
IN_DIM = 32


def _make_init(model):
    def init(rng, batch_inputs):
        params = model.init(
            {"params": rng}, jnp.zeros_like(batch_inputs), train=False
        )["params"]
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adamw(1e-3), rng=rng
        )

    return init


def test_dp_loss_decreases(mesh_data8, rng):
    model = MLPClassifier(CFG)
    batch = classification_batch(jax.random.PRNGKey(0), 128, IN_DIM, CFG.num_classes)
    init_fn = dp.make_init(_make_init(model), mesh=mesh_data8)
    state = init_fn(rng, batch.inputs)

    step_fn = dp.make_train_step(
        make_classification_loss("data"),
        num_minibatches=4,
        mesh=mesh_data8,
        donate=False,
    )
    state, metrics0 = step_fn(state, None, batch)
    first = compute(metrics0)["loss"]
    for _ in range(15):
        state, metrics = step_fn(state, None, batch)
    last = compute(metrics)["loss"]
    assert last < first, f"loss did not decrease: {first} -> {last}"


def test_dp_matches_single_device(mesh_data8, rng):
    """Mean-pmean'd DP gradients == single-device full-batch training."""
    # fp32 so reduction-order differences between shardings stay below Adam's
    # sign-sensitivity (bf16's ~1e-2 relative error flips tiny gradients).
    cfg32 = MLPConfig(hidden_size=64, num_classes=10, dropout_rate=0.0, dtype=jnp.float32)
    model = MLPClassifier(cfg32)
    batch = classification_batch(jax.random.PRNGKey(1), 64, IN_DIM, cfg32.num_classes)
    loss_fn = make_classification_loss("data")

    init_fn = dp.make_init(_make_init(model), mesh=mesh_data8)
    state_dp = init_fn(rng, batch.inputs)
    step_dp = dp.make_train_step(loss_fn, num_minibatches=1, mesh=mesh_data8, donate=False)

    # single-device baseline: same init (rng unfolded => identical), plain jit
    mesh1 = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    init1 = dp.make_init(_make_init(model), mesh=mesh1)
    state_1 = init1(rng, batch.inputs)
    step_1 = dp.make_train_step(loss_fn, num_minibatches=1, mesh=mesh1, donate=False)

    for _ in range(3):
        state_dp, m_dp = step_dp(state_dp, None, batch)
        state_1, m_1 = step_1(state_1, None, batch)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        jax.device_get(state_dp.params),
        jax.device_get(state_1.params),
    )
    assert compute(m_dp)["loss"] == pytest.approx(compute(m_1)["loss"], rel=1e-4)


def test_dp_metrics_count_global_batch(mesh_data8, rng):
    model = MLPClassifier(CFG)
    batch = classification_batch(jax.random.PRNGKey(2), 128, IN_DIM, CFG.num_classes)
    init_fn = dp.make_init(_make_init(model), mesh=mesh_data8)
    state = init_fn(rng, batch.inputs)
    step_fn = dp.make_train_step(
        make_classification_loss("data"), num_minibatches=2, mesh=mesh_data8, donate=False
    )
    _, metrics = step_fn(state, None, batch)
    # psum over 8 devices x 128-sample global batch
    assert float(metrics["loss"][1]) == 128.0


def test_dp_donation_buffers(mesh_data8, rng):
    """Donated variant runs and returns fresh buffers."""
    model = MLPClassifier(CFG)
    batch = classification_batch(jax.random.PRNGKey(3), 64, IN_DIM, CFG.num_classes)
    init_fn = dp.make_init(_make_init(model), mesh=mesh_data8)
    state = init_fn(rng, batch.inputs)
    step_fn = dp.make_train_step(
        make_classification_loss("data"), num_minibatches=1, mesh=mesh_data8, donate=True
    )
    state, metrics = step_fn(state, None, batch)
    state, metrics = step_fn(state, metrics, batch)
    assert compute(metrics)["loss"] > 0
